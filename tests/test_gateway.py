"""The multi-replica routing gateway (cake_tpu/gateway).

`make gateway-smoke` acceptance: a 3-backend loopback fleet where SSE
streams through the gateway are bit-identical to a direct connection; a
backend killed mid-fleet has its traffic transparently retried onto the
survivors while the circuit breaker opens; prefix-affinity routing lands
same-prefix requests on one replica and measurably raises that replica's
engine prefix-store hits where round_robin's interleaving thrashes them;
a draining backend is routed around with zero client-visible 5xx; plus
policy/health unit coverage and the loadgen --retry-429 /
--spawn-backends smoke.
"""

import http.server
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from cake_tpu.gateway import policy as policy_mod
from cake_tpu.gateway.api import GatewayServer, parse_backends, start_gateway
from cake_tpu.gateway.health import (DOWN, DRAINING, UP, Backend,
                                     HealthMonitor)
from cake_tpu.gateway.policy import P2C, Prefix, RoundRobin, make_policy
from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.runtime.retry import RetryPolicy
from cake_tpu.serve.api import start_api_server
from cake_tpu.serve.scheduler import Scheduler

# eos disabled: deterministic stream lengths (the test_serve convention)
CFG = tiny(max_seq_len=64, eos_token_id=-1)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)

# unique per-test-session backend names so per-backend metric series
# never collide between monitors built by different tests
_NAME_SEQ = iter(range(10_000))


def _backend(addr: str) -> Backend:
    return Backend(f"t{next(_NAME_SEQ)}", addr)


def _monitor(addrs, **kw) -> HealthMonitor:
    kw.setdefault("probe_interval", 0.2)
    kw.setdefault("up_after", 1)
    return HealthMonitor([_backend(a) for a in addrs], **kw)


class _FakeTok:
    """id -> letter (alnum decodes, so the detok emits text per token)."""

    def decode(self, ids):
        return "".join(chr(ord("a") + (i % 26)) for i in ids)

    def encode(self, text):
        return [ord(c) - ord("a") for c in text]


# -- helpers ----------------------------------------------------------------


def _get(url: str, timeout: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post(url: str, body: dict, timeout: float = 120.0):
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _post_sse(url: str, body: dict, timeout: float = 120.0):
    """Stream one request; returns (parsed events, raw data-line bytes)."""
    body = dict(body, stream=True)
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    events, raw_lines = [], []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        for raw in r:
            raw = raw.strip()
            if not raw.startswith(b"data: "):
                continue
            raw_lines.append(raw)
            data = raw[len(b"data: "):]
            events.append(data.decode() if data == b"[DONE]"
                          else json.loads(data))
    return events, raw_lines


def _ids_of(events):
    return [e["token"] for e in events
            if isinstance(e, dict) and "token" in e]


def _done_of(events):
    done = [e for e in events if isinstance(e, dict) and e.get("done")]
    assert len(done) == 1, f"expected one terminal event, got {events}"
    return done[0]


# -- scripted stand-in replicas (failure paths without engine weight) -------


class _StubBackend:
    """Scripted serve-replica stand-in: real /healthz + /v1/completions
    shapes, behavior set by ``mode`` — ok | error500 | reject429 |
    draining | flaky429 (429 once, then ok)."""

    def __init__(self, mode: str = "ok", tokens: int = 4,
                 token_delay_s: float = 0.0, unary_delay_s: float = 0.0,
                 queued: int = 0, running: int = 0,
                 max_concurrent: int = 4, retry_after: str = "3"):
        self.mode = mode
        self.tokens = tokens
        self.token_delay_s = token_delay_s
        self.unary_delay_s = unary_delay_s
        self.load = {"queued": queued, "running": running,
                     "max_concurrent": max_concurrent, "tok_s_ema": 50.0}
        self.retry_after = retry_after
        self.completions = 0
        self.rejects = 0
        self._lock = threading.Lock()
        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, status, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.rstrip("/") or "/"
                if path == "/healthz":
                    if stub.mode == "draining":
                        self._json(503, {"ok": False, "draining": True})
                    else:
                        self._json(200, dict(stub.load, ok=True,
                                             draining=False))
                elif path == "/v1/models":
                    self._json(200, {"object": "list",
                                     "data": [{"id": "stub"}]})
                else:
                    self._json(404, {"error": "no route"})

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                mode = stub.mode
                if mode == "flaky429":
                    with stub._lock:
                        first = stub.rejects == 0
                        if first:
                            stub.rejects += 1
                    mode = "reject429" if first else "ok"
                if mode == "error500":
                    self._json(500, {"error": "stub exploded"})
                    return
                if mode == "reject429":
                    with stub._lock:
                        stub.rejects += 1
                    self._json(429, {"error": "stub saturated"},
                               headers={"Retry-After": stub.retry_after})
                    return
                if mode == "draining":
                    self._json(503, {"error": "server is draining"})
                    return
                with stub._lock:
                    stub.completions += 1
                n = min(int(body.get("max_tokens", 16)), stub.tokens)
                ids = list(range(7, 7 + n))
                if not body.get("stream"):
                    if stub.unary_delay_s:
                        time.sleep(stub.unary_delay_s)  # "generation"
                    self._json(200, {
                        "id": "stub", "finish_reason": "length",
                        "usage": {"prompt_tokens": 1,
                                  "completion_tokens": n,
                                  "total_tokens": 1 + n},
                        "token_ids": ids})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                for i, t in enumerate(ids):
                    if stub.token_delay_s:
                        time.sleep(stub.token_delay_s)
                    self.wfile.write(
                        f"data: {json.dumps({'index': i, 'token': t, 'text': None})}\n\n".encode())
                    self.wfile.flush()
                done = {"id": "stub", "done": True,
                        "finish_reason": "length",
                        "usage": {"completion_tokens": n}}
                self.wfile.write(f"data: {json.dumps(done)}\n\n".encode())
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                     Handler)
        self.port = self.httpd.server_address[1]
        self.addr = f"127.0.0.1:{self.port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def stub_gateway():
    """Factory: gateway + monitor over a list of stub/real addresses;
    everything torn down at test end."""
    created = []

    def build(addrs, policy="round_robin", initial_probe=True,
              **monitor_kw):
        mon = _monitor(addrs, **monitor_kw)
        mon.start(initial_probe=initial_probe)
        gw = start_gateway(mon, make_policy(policy, prefix_block=8),
                           connect_timeout=1.0, read_timeout=60.0)
        created.append((gw, mon))
        return gw, mon

    yield build
    for gw, mon in created:
        gw.close()
        mon.stop()


def _url(gw) -> str:
    return f"http://127.0.0.1:{gw.port}"


def _dead_addr() -> str:
    """An address nothing listens on (bind, grab the port, close)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    return f"127.0.0.1:{port}"


# -- policy units -----------------------------------------------------------


def test_round_robin_cycles():
    bs = [_backend(f"127.0.0.1:{9000 + i}") for i in range(3)]
    rr = RoundRobin()
    picks = [rr.choose(bs).name for _ in range(6)]
    assert picks == [b.name for b in bs] * 2


def test_p2c_prefers_lower_load():
    idle, busy = _backend("127.0.0.1:9000"), _backend("127.0.0.1:9001")
    busy.probe_ok({"queued": 5, "running": 4, "max_concurrent": 4}, 1)
    idle.probe_ok({"queued": 0, "running": 1, "max_concurrent": 4}, 1)
    p2c = P2C()
    picks = {p2c.choose([idle, busy]).name for _ in range(16)}
    assert picks == {idle.name}  # two choices always include both here


def test_prefix_is_sticky_and_falls_back_when_saturated():
    bs = [_backend(f"127.0.0.1:{9100 + i}") for i in range(3)]
    pol = Prefix(block=8)
    key = policy_mod.prefix_key({"prompt": "system prompt here!"}, 8)
    assert key is not None
    first = pol.choose(bs, key=key)
    for _ in range(8):  # deterministic: same key -> same replica
        assert pol.choose(bs, key=key) is first
    # a different candidate ORDER must not move the key (rendezvous)
    assert pol.choose(list(reversed(bs)), key=key) is first
    # saturated preferred -> p2c over the rest
    first.probe_ok({"queued": 3, "running": 4, "max_concurrent": 4}, 1)
    fallback = pol.choose(bs, key=key)
    assert fallback is not first
    # no key (short prompt) -> p2c, not a crash
    assert pol.choose(bs, key=None) in bs


def test_prefix_key_alignment():
    assert policy_mod.prefix_key({"prompt_ids": list(range(16))}, 8) \
        == policy_mod.prefix_key({"prompt_ids": list(range(8)) + [99] * 8},
                                 8)
    assert policy_mod.prefix_key({"prompt_ids": [1, 2, 3]}, 8) is None
    assert policy_mod.prefix_key({"prompt": "ab"}, 8) is None
    assert policy_mod.prefix_key({}, 8) is None
    k1 = policy_mod.prefix_key({"prompt": "abcdefgh-SUFFIX1"}, 8)
    k2 = policy_mod.prefix_key({"prompt": "abcdefgh-SUFFIX2"}, 8)
    assert k1 == k2 is not None


def test_prefix_counters_score_first_choice_only():
    """Review regression: a retry lands on the rendezvous runner-up
    because the true preferred replica was already excluded — that must
    not read as an affinity hit (or fallback) in the routing-decision
    counters."""
    bs = [_backend(f"127.0.0.1:{9150 + i}") for i in range(3)]
    pol = Prefix(block=8)
    key = policy_mod.prefix_key({"prompt": "x" * 8}, 8)
    hits0 = policy_mod.PREFIX_HITS.value
    fb0 = policy_mod.PREFIX_FALLBACK.value
    pol.choose(bs[:2], key=key, first_attempt=False)  # the retry path
    assert policy_mod.PREFIX_HITS.value == hits0
    assert policy_mod.PREFIX_FALLBACK.value == fb0
    pol.choose(bs, key=key)  # the first attempt still scores
    assert (policy_mod.PREFIX_HITS.value
            + policy_mod.PREFIX_FALLBACK.value) == hits0 + fb0 + 1


def test_parse_backends_validation():
    bs = parse_backends("127.0.0.1:8081, 127.0.0.1:8082")
    assert [b.port for b in bs] == [8081, 8082]
    assert [b.name for b in bs] == ["b0", "b1"]
    with pytest.raises(ValueError):
        parse_backends("127.0.0.1:8081,127.0.0.1:8081")  # duplicate
    with pytest.raises(ValueError):
        parse_backends("no-port")
    with pytest.raises(ValueError):
        parse_backends("")


# -- health state machine units ---------------------------------------------


def test_backend_down_after_failures_and_breaker_backoff():
    import random as random_mod

    b = _backend("127.0.0.1:9200")
    pol = RetryPolicy(deadline_s=None, max_attempts=1 << 30, base_s=0.5,
                      cap_s=2.0)
    rng = random_mod.Random(7)
    b.report_failure(pol, rng, down_after=2, now=100.0)
    assert b.state == UP  # hysteresis: one failure is not an outage
    b.report_failure(pol, rng, down_after=2, now=100.1)
    assert b.state == DOWN
    assert not b.routable()
    # breaker: the next probe is backed off into the future
    assert not b.probe_due(100.1)
    assert b.breaker_open(100.1)
    assert b.probe_due(200.0)
    # hysteresis up: up_after=2 needs two clean probes
    b.probe_ok({"queued": 0}, up_after=2)
    assert b.state == DOWN
    b.probe_ok({"queued": 0}, up_after=2)
    assert b.state == UP
    assert not b.breaker_open(200.0)


def test_backend_draining_is_immediate_both_ways():
    b = _backend("127.0.0.1:9201")
    b.probe_draining()
    assert b.state == DRAINING and not b.routable()
    b.probe_ok({}, up_after=3)  # the backend said it is back: no waiting
    assert b.state == UP


def test_backend_saturation_signal():
    b = _backend("127.0.0.1:9202")
    assert not b.saturated(now=10.0)
    b.probe_ok({"queued": 2, "running": 4, "max_concurrent": 4}, 1)
    assert b.saturated(now=10.0)
    b.probe_ok({"queued": 0, "running": 1, "max_concurrent": 4}, 1)
    assert not b.saturated(now=10.0)
    b.report_saturated(5.0, now=10.0)  # a 429 said so, believe it a while
    assert b.saturated(now=12.0)
    assert not b.saturated(now=16.0)


def test_initial_probe_is_decisive():
    """Review regression: the bootstrap probe pass collapses the DOWN
    hysteresis — a backend refusing its very FIRST probe has no history
    to flap against, so the gateway must not start routing toward it on
    pure optimism (down_after only buffers established backends)."""
    mon = _monitor([_dead_addr()], down_after=2, probe_interval=30.0)
    mon.start()
    try:
        assert mon.backends[0].state == DOWN
        assert mon.routable() == []
    finally:
        mon.stop()


def test_server_prefix_block_follows_policy():
    """Review regression: the affinity alignment has ONE source of truth
    — a Prefix policy's block wins over the server-level default, so the
    key is always computed at the block the policy hashes with."""
    mon = _monitor([_dead_addr()])  # never started: no probes needed
    gw = GatewayServer(mon, make_policy("prefix", prefix_block=8))
    try:
        assert gw.prefix_block == 8
        gw2 = GatewayServer(mon, make_policy("p2c"), prefix_block=16)
        try:
            assert gw2.prefix_block == 16
        finally:
            gw2.httpd.server_close()
    finally:
        gw.httpd.server_close()


def test_monitor_probes_mark_states(stub_gateway):
    ok, draining = _StubBackend("ok"), _StubBackend("draining")
    dead = _dead_addr()
    try:
        _, mon = stub_gateway([ok.addr, draining.addr, dead],
                              down_after=1)
        deadline = time.time() + 10
        while time.time() < deadline:
            states = [b.state for b in mon.backends]
            if states == [UP, DRAINING, DOWN]:
                break
            time.sleep(0.05)
        assert [b.state for b in mon.backends] == [UP, DRAINING, DOWN]
        # the load signal rode the same healthz GET
        assert mon.backends[0].describe()["load"]["max_concurrent"] == 4
    finally:
        ok.close()
        draining.close()


# -- proxy semantics over stubs ---------------------------------------------


def test_connect_failure_retries_to_survivor_and_opens_breaker(
        stub_gateway):
    from cake_tpu.gateway import api as gw_api

    ok = _StubBackend("ok")
    try:
        # initial_probe=False: the backend "dies" after a clean start, so
        # the first failure the gateway sees is the routed request itself
        # — the passive-signal path under test
        gw, mon = stub_gateway([_dead_addr(), ok.addr],
                               policy="round_robin", down_after=2,
                               probe_interval=30.0, initial_probe=False)
        retries0 = gw_api.RETRIES.value
        for i in range(4):  # round robin keeps picking the dead one first
            out = _post(_url(gw), {"prompt_ids": [1, 2], "max_tokens": 3})
            assert out["usage"]["completion_tokens"] == 3
        assert gw_api.RETRIES.value > retries0
        dead_b = mon.backends[0]
        assert dead_b.state == DOWN  # passive failures tripped the breaker
        assert dead_b.breaker_open()
        assert ok.completions == 4
    finally:
        ok.close()


def test_5xx_before_first_byte_retries_transparently(stub_gateway):
    bad, good = _StubBackend("error500"), _StubBackend("ok")
    try:
        gw, mon = stub_gateway([bad.addr, good.addr],
                               policy="round_robin", down_after=3,
                               probe_interval=30.0)
        events, _ = _post_sse(_url(gw),
                              {"prompt_ids": [1], "max_tokens": 4})
        assert _ids_of(events) == [7, 8, 9, 10]
        assert _done_of(events)["finish_reason"] == "length"
        assert mon.backends[0].describe()["errors"] >= 1
    finally:
        bad.close()
        good.close()


def test_pre_first_byte_retry_span_parented_under_route(stub_gateway):
    """ISSUE 16: a transparent pre-first-byte retry leaves its own
    gateway.retry span nested under gateway.route, on the SAME trace id
    the client sent — and the gateway emits its flight record + mirrors
    spans into the tracer (the --trace/--flight-log artifacts on --mode
    gateway)."""
    import os

    from cake_tpu.obs import flight as obs_flight
    from cake_tpu.obs import reqtrace
    from cake_tpu.obs import trace as obs_trace

    bad, good = _StubBackend("error500"), _StubBackend("ok")
    tid = os.urandom(16).hex()
    root = os.urandom(8).hex()
    obs_trace.tracer().start(max_events=100_000)
    obs_flight.recorder().enable()
    obs_flight.recorder().clear()
    try:
        gw, mon = stub_gateway([bad.addr, good.addr],
                               policy="round_robin", down_after=3,
                               probe_interval=30.0)
        req = urllib.request.Request(
            _url(gw) + "/v1/completions",
            data=json.dumps({"prompt_ids": [1], "max_tokens": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json",
                     reqtrace.HEADER: f"00-{tid}-{root}-01"})
        with urllib.request.urlopen(req, timeout=60) as r:
            r.read()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            tl = reqtrace.request_log().get(tid)
            if tl is not None and {"gateway.route", "gateway.retry"} <= \
                    {s["name"] for s in tl["spans"]}:
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                f"no retry-bearing timeline for {tid}: "
                f"{tl and [s['name'] for s in tl['spans']]}")
        route = next(s for s in tl["spans"]
                     if s["name"] == "gateway.route")
        retry = next(s for s in tl["spans"]
                     if s["name"] == "gateway.retry")
        assert retry["parent"] == route["span"]
        assert route["parent"] == root  # the client's own span chains up
        # the artifacts a gateway-mode run flushes: flight record + trace
        recs = [r for r in obs_flight.recorder().records()
                if r.get("kind") == "gateway.request"
                and r.get("trace") == tid]
        assert recs and recs[0]["ok"] and recs[0]["tokens"] == 4
        assert recs[0]["ttft_ms"] > 0
        doc = obs_trace.tracer().to_chrome_trace()
        traced = {e["name"] for e in doc["traceEvents"]
                  if e.get("args", {}).get("trace") == tid}
        assert {"gateway.route", "gateway.retry"} <= traced
        # the gateway serves the same timeline on its own debug
        # endpoint (merged with whatever the backends know — stubs
        # know nothing, best-effort); unknown ids still 404
        served = _get(_url(gw) + f"/v1/requests/{tid}")
        assert {"gateway.route", "gateway.retry"} <= \
            {s["name"] for s in served["spans"]}
        assert served["trace_id"] == tid
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(_url(gw) + "/v1/requests/" + "0" * 32)
        assert exc.value.code == 404
    finally:
        obs_trace.tracer().stop()
        obs_trace.tracer().clear()
        obs_flight.recorder().close()
        bad.close()
        good.close()


def test_gateway_slo_judges_client_view(stub_gateway):
    """The gateway's --slo-ttft-ms/--slo-tpot-ms accounting: verdicts
    land on /healthz (burn block) and in the request timeline."""
    from cake_tpu.obs import reqtrace

    ok = _StubBackend("ok")
    mon = _monitor([ok.addr], probe_interval=30.0)
    mon.start()
    slo = reqtrace.SloTracker(
        reqtrace.SloPolicy(ttft_ms=60_000.0, tpot_ms=60_000.0))
    gw = start_gateway(mon, make_policy("round_robin"),
                       connect_timeout=1.0, read_timeout=60.0, slo=slo)
    try:
        g0 = slo._good.value
        out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 3})
        assert out["usage"]["completion_tokens"] == 3
        deadline = time.monotonic() + 5.0
        while slo._good.value <= g0:
            assert time.monotonic() < deadline, "no SLO verdict landed"
            time.sleep(0.05)
        health = _get(_url(gw) + "/healthz")
        assert health["slo"]["window_n"] >= 1
        assert health["slo"]["burn_short"] == 0.0
        assert health["slo"]["ttft_target_ms"] == 60_000.0
    finally:
        gw.close()
        mon.stop()
        ok.close()


def test_429_sheds_only_when_every_backend_saturated(stub_gateway):
    from cake_tpu.gateway import api as gw_api

    sat1 = _StubBackend("reject429", retry_after="7")
    sat2 = _StubBackend("reject429", retry_after="7")
    ok = _StubBackend("ok")
    try:
        # one healthy replica: the client must never see the 429
        gw, _ = stub_gateway([sat1.addr, sat2.addr, ok.addr],
                             policy="round_robin", probe_interval=30.0)
        out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
        assert out["usage"]["completion_tokens"] == 2

        # every replica saturated: admission control waits its bounded
        # budget, then SHEDS with a Retry-After derived from fleet-wide
        # tok/s (ISSUE 19) — the backend's own "7" is NOT relayed
        sat0 = gw_api.SATURATED.value
        shed0 = gw_api.SHED.value
        gw2, _ = stub_gateway([sat1.addr, sat2.addr],
                              policy="round_robin", probe_interval=30.0)
        gw2.admit_wait_s = 0.2  # keep the bounded wait short here
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(_url(gw2), {"prompt_ids": [1], "max_tokens": 2})
        assert exc.value.code == 429
        body = json.loads(exc.value.read())
        assert body["shed"] is True
        assert 1 <= body["retry_after_s"] <= 30
        assert (int(exc.value.headers["Retry-After"])
                == body["retry_after_s"])
        assert gw_api.SATURATED.value > sat0
        assert gw_api.SHED.value > shed0
    finally:
        sat1.close()
        sat2.close()
        ok.close()


def test_draining_backend_routed_around_with_zero_5xx(stub_gateway):
    draining = _StubBackend("draining")
    ok1, ok2 = _StubBackend("ok"), _StubBackend("ok")
    try:
        gw, mon = stub_gateway([draining.addr, ok1.addr, ok2.addr],
                               policy="round_robin")
        deadline = time.time() + 10
        while (time.time() < deadline
               and mon.backends[0].state != DRAINING):
            time.sleep(0.05)
        assert mon.backends[0].state == DRAINING
        for i in range(6):  # zero 5xx: every request lands on a survivor
            out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
            assert out["usage"]["completion_tokens"] == 2
        assert draining.completions == 0
        assert ok1.completions + ok2.completions == 6
    finally:
        draining.close()
        ok1.close()
        ok2.close()


def test_draining_backend_503_is_retried_even_before_probe(stub_gateway):
    """A replica that starts draining BETWEEN probes: its 503 is a
    passive signal — the request retries elsewhere and the state flips
    without waiting for the next poll."""
    draining, ok = _StubBackend("draining"), _StubBackend("ok")
    try:
        gw, mon = stub_gateway([draining.addr, ok.addr],
                               policy="round_robin", probe_interval=30.0)
        # force the draining replica to be picked first at least once
        for i in range(3):
            out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
            assert out["usage"]["completion_tokens"] == 2
        assert mon.backends[0].state == DRAINING
        assert draining.completions == 0
    finally:
        draining.close()
        ok.close()


def test_gateway_healthz_models_status_metrics(stub_gateway):
    ok = _StubBackend("ok")
    try:
        gw, _ = stub_gateway([ok.addr])
        health = _get(_url(gw) + "/healthz")
        assert health["ok"] is True and health["backends_up"] == 1
        entry = next(iter(health["backends"].values()))
        assert entry["state"] == UP
        assert entry["registered_via"] == "static"
        assert entry["lease_expires_in_s"] is None  # static: no lease
        assert entry["last_probe_age_s"] is not None
        models = _get(_url(gw) + "/v1/models")
        assert models["data"][0]["id"] == "stub"
        status = _get(_url(gw) + "/")
        assert status["role"] == "gateway"
        assert status["backends"][0]["state"] == UP
        text = urllib.request.urlopen(
            _url(gw) + "/metrics", timeout=10).read().decode()
        for series in ("cake_gateway_requests", "cake_gateway_retries",
                       "cake_gateway_backends_up", "cake_gateway_added_ms"):
            assert series in text, f"{series} missing from /metrics"
    finally:
        ok.close()


def test_added_ms_excludes_backend_generation_time(stub_gateway):
    """Review regression: gateway.added_ms is the gateway's OWN overhead
    (route + connect + request send). A unary backend that takes 1s to
    generate must not push a ~1000 ms sample into the histogram — the
    response wait is the backend working, not the gateway adding."""
    from cake_tpu.gateway import api as gw_api

    slow = _StubBackend("ok", unary_delay_s=1.0)
    try:
        gw, _ = stub_gateway([slow.addr], probe_interval=30.0)
        before = gw_api.ADDED_MS.snapshot()
        t0 = time.perf_counter()
        out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
        wall_ms = (time.perf_counter() - t0) * 1e3
        assert out["usage"]["completion_tokens"] == 2
        after = gw_api.ADDED_MS.snapshot()
        assert after["count"] == before["count"] + 1
        sample_ms = after["sum"] - before["sum"]
        assert wall_ms >= 1000  # the backend really did take ~1s
        assert sample_ms < 500, (
            f"added_ms recorded {sample_ms:.0f} ms — it is counting the "
            "backend's generation time")
    finally:
        slow.close()


def test_gateway_healthz_503_when_no_backend_up(stub_gateway):
    gw, _ = stub_gateway([_dead_addr()], down_after=1)
    deadline = time.time() + 10
    code = None
    while time.time() < deadline and code != 503:
        try:
            _get(_url(gw) + "/healthz")
        except urllib.error.HTTPError as e:
            code = e.code
        time.sleep(0.05)
    assert code == 503
    # and a routed request is refused, not hung
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
    assert exc.value.code == 503


def test_gateway_drain_finishes_inflight_and_refuses_new():
    slow = _StubBackend("ok", tokens=20, token_delay_s=0.05)
    mon = _monitor([slow.addr])
    mon.start()
    gw = start_gateway(mon, make_policy("round_robin"))
    try:
        result: dict = {}

        def client():
            ev, _ = _post_sse(f"http://127.0.0.1:{gw.port}",
                              {"prompt_ids": [1], "max_tokens": 20})
            result["events"] = ev

        t = threading.Thread(target=client)
        t.start()
        deadline = time.time() + 10  # wait for the stream to be in flight
        while time.time() < deadline and slow.completions == 0:
            time.sleep(0.02)
        assert slow.completions == 1
        drainer = threading.Thread(target=lambda: gw.drain(timeout_s=30))
        drainer.start()
        deadline = time.time() + 5
        while time.time() < deadline and not gw.is_draining():
            time.sleep(0.01)
        from cake_tpu.gateway import api as gw_api

        req0, rej0 = gw_api.REQUESTS.value, gw_api.REJECTED.value
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(f"http://127.0.0.1:{gw.port}",
                  {"prompt_ids": [2], "max_tokens": 2}, timeout=10)
        assert exc.value.code == 503  # refused while draining
        # review regression: a drain-refused request is rejected only —
        # gateway.requests counts ACCEPTED requests
        assert gw_api.REQUESTS.value == req0
        assert gw_api.REJECTED.value == rej0 + 1
        t.join(timeout=30)
        drainer.join(timeout=30)
        assert len(_ids_of(result["events"])) == 20  # in-flight finished
    finally:
        gw.close()
        mon.stop()


# -- the real 3-backend loopback fleet --------------------------------------


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(7))


@pytest.fixture(scope="module")
def fleet(params):
    """Three real serve replicas over the batch engine. Small prefix
    knobs (share_min=8, block=8, ONE store entry) so prefix-affinity
    effects are observable within tiny prompts — and so round_robin's
    interleaving measurably thrashes the store."""
    stacks = []
    for _ in range(3):
        gen = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                             settings=SamplerSettings(**GREEDY),
                             prefix_share_min=8, prefix_block=8,
                             prefix_cache_entries=1)
        sched = Scheduler(gen, queue_depth=8, request_timeout_s=120)
        sched.start(max_concurrent=2)
        srv = start_api_server(sched)
        stacks.append({"srv": srv, "sched": sched, "gen": gen,
                       "addr": f"127.0.0.1:{srv.port}"})
    yield stacks
    for s in stacks:
        s["srv"].close()
        s["sched"].close()


def test_serve_healthz_carries_load_fields(fleet):
    """The satellite contract: the gateway's whole p2c signal is one
    /healthz GET on the serve plane."""
    health = _get(f"http://{fleet[0]['addr']}/healthz")
    for field in ("ok", "draining", "queued", "running",
                  "max_concurrent", "tok_s_ema"):
        assert field in health, f"/healthz missing {field}"
    assert health["max_concurrent"] == 2


def test_sse_bit_identical_through_gateway(fleet, stub_gateway):
    """The headline pass-through contract: every token event an SSE
    client sees through the gateway is byte-identical to a direct
    connection (the gateway never reframes), and unary responses carry
    identical ids."""
    gw, _ = stub_gateway([s["addr"] for s in fleet])
    body = {"prompt": "abcd", "max_tokens": 8}
    direct_ev, direct_raw = _post_sse(f"http://{fleet[0]['addr']}", body)
    gw_ev, gw_raw = _post_sse(_url(gw), body)
    # token events byte-for-byte (the terminal usage block carries
    # per-request timing, so it is compared structurally instead)
    assert [r for r in gw_raw if b'"token"' in r] \
        == [r for r in direct_raw if b'"token"' in r]
    assert _ids_of(gw_ev) == _ids_of(direct_ev)
    d_direct, d_gw = _done_of(direct_ev), _done_of(gw_ev)
    assert d_gw["finish_reason"] == d_direct["finish_reason"]
    assert (d_gw["usage"]["completion_tokens"]
            == d_direct["usage"]["completion_tokens"])
    assert gw_raw[-1] == b"data: [DONE]"
    # unary parity
    direct_out = _post(f"http://{fleet[0]['addr']}", body)
    gw_out = _post(_url(gw), body)
    assert gw_out["token_ids"] == direct_out["token_ids"]
    assert gw_out["text"] == direct_out["text"]


def test_concurrent_sse_through_gateway_match_solo(fleet, stub_gateway):
    """4 concurrent SSE clients through the p2c gateway: every stream
    matches its solo run (engine batch-composition invariance survives
    the extra hop and the load-aware scatter)."""
    gw, _ = stub_gateway([s["addr"] for s in fleet], policy="p2c")
    prompts = ["abcd", "bcde", "cdef", "defg"]
    solo = {}
    for p in prompts:
        ev, _ = _post_sse(_url(gw), {"prompt": p, "max_tokens": 6})
        solo[p] = _ids_of(ev)
        assert len(solo[p]) == 6
    results: dict = {}

    def client(p):
        ev, _ = _post_sse(_url(gw), {"prompt": p, "max_tokens": 6})
        results[p] = _ids_of(ev)

    threads = [threading.Thread(target=client, args=(p,)) for p in prompts]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for p in prompts:
        assert results[p] == solo[p], f"stream for {p!r} diverged"


def _affinity_groups(backends, block=8):
    """Two 8-char prefix groups that rendezvous-hash to two DIFFERENT
    backends (searched deterministically so the thrash-vs-hit comparison
    is meaningful even if one pair collides)."""
    pol = Prefix(block=block)
    alphabet = "abcdefghijklmnopqrstuvwxyz"
    first = alphabet[0] * block
    pref_first = pol.choose(backends,
                            key=policy_mod.prefix_key({"prompt": first},
                                                      block))
    for c in alphabet[1:]:
        cand = c * block
        pref = pol.choose(backends,
                          key=policy_mod.prefix_key({"prompt": cand},
                                                    block))
        if pref is not pref_first:
            return first, cand
    raise AssertionError("no distinct-backend prefix pair found")


def test_prefix_affinity_raises_prefix_store_hits(fleet, stub_gateway):
    """The fleet-wide-cache claim: same-prefix requests land on ONE
    replica under the prefix policy and its engine prefix-store hits
    climb, while round_robin's interleaving (one store entry per engine)
    thrashes and hits stay flat."""

    def run_leg(policy):
        gw, mon = stub_gateway([s["addr"] for s in fleet], policy=policy)
        hits0 = [s["gen"].stats()["prefix_hits"] for s in fleet]
        reqs0 = [b.requests.value for b in mon.backends]
        a, b = _affinity_groups(mon.backends)
        for i in range(4):  # alternating groups, sequential requests
            for prefix in (a, b):
                out = _post(_url(gw),
                            {"prompt": prefix + "wxyz"[i] * 4,
                             "max_tokens": 2})
                assert out["usage"]["completion_tokens"] == 2
        hits = sum(s["gen"].stats()["prefix_hits"] for s in fleet) \
            - sum(hits0)
        reqs = [b.requests.value - r0
                for b, r0 in zip(mon.backends, reqs0)]
        return hits, reqs

    rr_hits, _ = run_leg("round_robin")
    px_hits, px_reqs = run_leg("prefix")
    # prefix affinity: the 8 requests landed on exactly the two preferred
    # replicas, 4 each — and the store actually paid off
    assert sorted(px_reqs) == [0, 4, 4], f"affinity scatter: {px_reqs}"
    assert px_hits >= 4, f"prefix store never hit: {px_hits}"
    # round robin interleaves the groups across every 1-entry store:
    # consecutive same-prefix admissions never meet, hits stay flat
    assert rr_hits == 0, f"round_robin unexpectedly hit: {rr_hits}"
    assert px_hits > rr_hits


def test_loadgen_retry_429_resubmits():
    flaky = _StubBackend("flaky429", retry_after="0")
    try:
        from cake_tpu.tools import loadgen

        stats = loadgen.run_load(f"http://{flaky.addr}", 3, concurrency=1,
                                 max_tokens=2, prompt_lens=[2], vocab=50,
                                 retry_429=True)
        assert stats["completed"] == 3
        assert stats["rejected_429"] == 0
        assert stats["retried_429"] >= 1
    finally:
        flaky.close()


def test_loadgen_counts_429_without_retry():
    sat = _StubBackend("reject429")
    try:
        from cake_tpu.tools import loadgen

        stats = loadgen.run_load(f"http://{sat.addr}", 2, concurrency=1,
                                 max_tokens=2, prompt_lens=[2], vocab=50)
        assert stats["completed"] == 0
        assert stats["rejected_429"] == 2
        assert stats["retried_429"] == 0
    finally:
        sat.close()


def test_loadgen_through_gateway(fleet, stub_gateway):
    """The loadgen driver against the real fleet through the gateway —
    the gateway-smoke traffic shape."""
    from cake_tpu.tools import loadgen

    gw, _ = stub_gateway([s["addr"] for s in fleet], policy="p2c")
    stats = loadgen.run_load(_url(gw), 6, concurrency=3, max_tokens=4,
                             prompt_lens=[4, 8], vocab=200, seed=3,
                             retry_429=True)
    assert stats["completed"] == 6 and stats["errors"] == 0
    assert stats["tokens"] == 24


def test_gateway_cli_validation():
    """--mode gateway flag surface: the guards that keep misconfiguration
    loud (no silent ignores), without starting a server."""
    from cake_tpu import cli

    # an empty --backends is VALID since the fleet plane (ISSUE 19) —
    # membership forms from self-registrations — so the misconfig
    # guards below are what is left to keep loud
    with pytest.raises(SystemExit, match="--lease-ttl"):
        cli.main(["--mode", "gateway", "--lease-ttl", "0"])
    with pytest.raises(SystemExit, match="--admit-wait"):
        cli.main(["--mode", "gateway", "--admit-wait", "-1"])
    with pytest.raises(SystemExit, match="--admit-queue"):
        cli.main(["--mode", "gateway", "--admit-queue", "0"])
    with pytest.raises(SystemExit, match="--register-with"):
        cli.main(["--mode", "gateway", "--backends", "127.0.0.1:1",
                  "--register-with", "http://127.0.0.1:2"])
    with pytest.raises(SystemExit, match="--model"):
        cli.main(["--mode", "gateway", "--backends", "127.0.0.1:1",
                  "--model", "x"])
    with pytest.raises(SystemExit, match="--mode gateway"):
        cli.main(["--model", "x", "--backends", "127.0.0.1:1"])
    with pytest.raises(SystemExit, match="--max-concurrent"):
        cli.main(["--mode", "gateway", "--backends", "127.0.0.1:1",
                  "--max-concurrent", "4"])
    with pytest.raises(SystemExit, match="--probe-interval"):
        cli.main(["--mode", "gateway", "--backends", "127.0.0.1:1",
                  "--probe-interval", "0"])
    with pytest.raises(SystemExit, match="--fetch"):
        cli.main(["--mode", "gateway", "--backends", "127.0.0.1:1",
                  "--fetch", "hf://org/m"])
    with pytest.raises(SystemExit, match="--model is required"):
        cli.main(["--mode", "serve"])


def test_loadgen_spawn_backends_smoke():
    """One command drives a whole loopback fleet: --spawn-backends N
    builds N tiny replicas + a gateway in process and the load runs
    clean through it."""
    from cake_tpu.tools import loadgen

    rc = loadgen.main(["--spawn-backends", "2", "-n", "6", "-c", "2",
                       "--max-tokens", "3", "--prompt-len", "4",
                       "--retry-429"])
    assert rc == 0


# -- mid-fleet kill: LAST on purpose (it takes a real replica down) ---------


def test_kill_backend_mid_fleet_retries_to_survivors(fleet, stub_gateway):
    """The acceptance chaos case: a replica dies mid-fleet; queued
    requests transparently retry onto the survivors (zero client-visible
    failures) while the dead replica's breaker opens."""
    from cake_tpu.gateway import api as gw_api

    gw, mon = stub_gateway([s["addr"] for s in fleet],
                           policy="round_robin", down_after=2,
                           probe_interval=30.0)  # passive-signal path
    # warm: all three replicas serving through the gateway
    for i in range(3):
        out = _post(_url(gw), {"prompt": "abcd", "max_tokens": 2})
        assert out["usage"]["completion_tokens"] == 2
    # kill replica 1 (listener down: connects refuse)
    fleet[1]["srv"].close()
    retries0 = gw_api.RETRIES.value
    for i in range(8):  # round robin keeps offering the dead one
        ev, _ = _post_sse(_url(gw), {"prompt": "bcda", "max_tokens": 3})
        assert len(_ids_of(ev)) == 3, f"request {i} lost tokens"
        assert _done_of(ev)["finish_reason"] == "length"
    assert gw_api.RETRIES.value > retries0
    dead = mon.backends[1]
    assert dead.state == DOWN
    assert dead.breaker_open()
    # the gateway still reports healthy: survivors carry the fleet
    health = _get(_url(gw) + "/healthz")
    assert health["ok"] is True and health["backends_up"] == 2
