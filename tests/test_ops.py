import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.rope import rope_tables, apply_rope
from cake_tpu.ops.mlp import swiglu
from cake_tpu.ops import sampling
from cake_tpu.ops.sampling import SamplerSettings, sample_token
from cake_tpu.ops.kvcache import init_cache, update_layer
from cake_tpu.models.config import tiny


def test_rms_norm_matches_numpy():
    x = np.random.RandomState(0).randn(2, 5, 16).astype(np.float32)
    w = np.random.RandomState(1).randn(16).astype(np.float32)
    eps = 1e-5
    expected = x / np.sqrt((x**2).mean(-1, keepdims=True) + eps) * w
    got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_rope_zero_position_of_first_token_is_identity():
    cos, sin = rope_tables(head_dim=8, max_seq=16, theta=10000.0)
    x = jnp.ones((1, 2, 1, 8))
    out = apply_rope(x, cos, sin, pos=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)


def test_rope_slice_matches_offset():
    """apply_rope(x, pos=k) on one token == apply_rope over k+1 tokens, last."""
    cos, sin = rope_tables(head_dim=8, max_seq=16, theta=10000.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 5, 8))
    full = apply_rope(x, cos, sin, pos=0)
    last = apply_rope(x[:, :, 4:5, :], cos, sin, pos=4)
    np.testing.assert_allclose(np.asarray(full[:, :, 4:5]), np.asarray(last), atol=1e-5)


def test_rope_preserves_norm():
    cos, sin = rope_tables(head_dim=16, max_seq=32, theta=10000.0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 7, 16))
    out = apply_rope(x, cos, sin, pos=3)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-4,
    )


LLAMA31_SCALING = {
    "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
    "high_freq_factor": 4.0, "original_max_position_embeddings": 8192,
}


def test_rope_llama3_scaling_matches_hf():
    """Golden: Llama-3.1 frequency scaling matches transformers' llama3 rule."""
    from types import SimpleNamespace

    torch = pytest.importorskip("torch")
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from cake_tpu.ops.rope import _scale_inv_freq

    head_dim, theta = 128, 500000.0
    hf_cfg = SimpleNamespace(
        rope_theta=theta, head_dim=head_dim, hidden_size=32 * head_dim,
        num_attention_heads=32, partial_rotary_factor=1.0,
        max_position_embeddings=8192, rope_scaling=LLAMA31_SCALING,
    )
    expected, _ = ROPE_INIT_FUNCTIONS["llama3"](hf_cfg, "cpu")
    base = 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))
    got = _scale_inv_freq(jnp.asarray(base, jnp.float32), LLAMA31_SCALING)
    np.testing.assert_allclose(
        np.asarray(got), expected.numpy(), rtol=1e-6, atol=0
    )


def test_rope_linear_scaling():
    from cake_tpu.ops.rope import _scale_inv_freq

    base = jnp.asarray([1.0, 0.1, 0.01], jnp.float32)
    got = _scale_inv_freq(base, {"rope_type": "linear", "factor": 4.0})
    np.testing.assert_allclose(np.asarray(got), np.asarray(base) / 4.0)
    with pytest.raises(ValueError, match="unsupported"):
        _scale_inv_freq(base, {"rope_type": "yarn", "factor": 2.0})
    # a malformed scaling dict with no type key must fail loudly, not be
    # silently applied as linear interpolation
    with pytest.raises(ValueError, match="no 'rope_type'"):
        _scale_inv_freq(base, {"factor": 4.0})


def test_config_carries_rope_scaling_to_generation():
    """from_hf_dict picks up rope_scaling and a scaled model generates
    (different positional geometry => different stream than unscaled)."""
    from cake_tpu.models.llama import init_params
    from cake_tpu.runtime.generator import LlamaGenerator

    scaling = dict(LLAMA31_SCALING, original_max_position_embeddings=32)
    cfg = tiny(max_seq_len=64)
    scaled = tiny(max_seq_len=64, rope_scaling=scaling)
    assert scaled.from_hf_dict(scaled.to_hf_dict()).rope_scaling == scaling
    params = init_params(cfg, jax.random.PRNGKey(0))
    streams = []
    for c in (cfg, scaled):
        g = LlamaGenerator(c, params,
                           settings=SamplerSettings(temperature=0.0))
        g.set_prompt(list(range(24)))
        streams.append([g.next_token(i).id for i in range(6)])
    assert streams[0] != streams[1]


def test_swiglu_matches_manual():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 8).astype(np.float32)
    wg = rs.randn(8, 16).astype(np.float32)
    wu = rs.randn(8, 16).astype(np.float32)
    wd = rs.randn(16, 8).astype(np.float32)
    g = x @ wg
    expected = ((g / (1 + np.exp(-g))) * (x @ wu)) @ wd
    got = swiglu(jnp.asarray(x), jnp.asarray(wg), jnp.asarray(wu), jnp.asarray(wd))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)


# -- KV cache ---------------------------------------------------------------

def test_kvcache_update_writes_at_pos():
    cfg = tiny()
    cache = init_cache(cfg, batch=1, max_seq=16)
    k_new = jnp.ones((1, cfg.num_key_value_heads, 2, cfg.head_dim))
    v_new = 2 * k_new
    k, v = update_layer(cache.k[0], cache.v[0], k_new, v_new, pos=3)
    assert float(k[0, 0, 3, 0]) == 1.0
    assert float(k[0, 0, 2, 0]) == 0.0
    assert float(v[0, 0, 4, 0]) == 2.0
    assert float(v[0, 0, 5, 0]) == 0.0


def test_kvcache_as_new_resets():
    cfg = tiny()
    cache = init_cache(cfg, batch=1, max_seq=8)
    k, v = update_layer(cache.k[0], cache.v[0],
                        jnp.ones((1, cfg.num_key_value_heads, 1, cfg.head_dim)),
                        jnp.ones((1, cfg.num_key_value_heads, 1, cfg.head_dim)),
                        pos=0)
    cache2 = cache.as_new()
    assert float(jnp.sum(cache2.k)) == 0.0
    assert cache2.k.shape == cache.k.shape


# -- Sampling ---------------------------------------------------------------

def test_repeat_penalty_matches_candle_semantics():
    logits = jnp.asarray([2.0, -2.0, 1.0, 0.5], jnp.float32)
    history = jnp.asarray([0, 1, -1, -1], jnp.int32)
    out = sampling.apply_repeat_penalty(logits, history, 2.0)
    np.testing.assert_allclose(
        np.asarray(out), [1.0, -4.0, 1.0, 0.5], rtol=1e-6
    )


def test_greedy_is_argmax():
    logits = jnp.asarray([0.1, 5.0, 0.2, 0.3], jnp.float32)
    history = jnp.full((4,), -1, jnp.int32)
    s = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    tok = sample_token(logits, jax.random.PRNGKey(0), history, s)
    assert int(tok) == 1


def test_top_k_restricts_support():
    logits = jnp.asarray([10.0, 9.0, 8.0, -5.0, -6.0], jnp.float32)
    history = jnp.full((4,), -1, jnp.int32)
    s = SamplerSettings(temperature=1.0, top_k=2, repeat_penalty=1.0)
    toks = {
        int(sample_token(logits, jax.random.PRNGKey(i), history, s))
        for i in range(50)
    }
    assert toks <= {0, 1}


def test_top_p_restricts_support():
    logits = jnp.asarray([10.0, 1.0, 0.0, -1.0], jnp.float32)
    history = jnp.full((4,), -1, jnp.int32)
    s = SamplerSettings(temperature=1.0, top_p=0.5, repeat_penalty=1.0)
    toks = {
        int(sample_token(logits, jax.random.PRNGKey(i), history, s))
        for i in range(50)
    }
    assert toks == {0}  # top token alone has > 0.5 of the mass


def test_sampling_is_seed_deterministic():
    logits = jax.random.normal(jax.random.PRNGKey(3), (100,))
    history = jnp.full((8,), -1, jnp.int32)
    s = SamplerSettings(temperature=0.8, top_k=10, repeat_penalty=1.0)
    a = int(sample_token(logits, jax.random.PRNGKey(7), history, s))
    b = int(sample_token(logits, jax.random.PRNGKey(7), history, s))
    assert a == b


def test_history_ring_buffer_wraps():
    hist, slot = sampling.init_history(4)
    for t in range(6):
        hist, slot = sampling.push_history(hist, slot, jnp.int32(t))
    assert sorted(np.asarray(hist).tolist()) == [2, 3, 4, 5]
