"""The request-serving plane (cake_tpu/serve): HTTP API + scheduler over
the continuous-batching engine.

`make serve-smoke` acceptance: concurrent SSE clients stream to completion
with per-stream output identical to their solo runs, a mid-run arrival is
admitted without stalling running streams, a disconnected client's slot is
reused, saturation answers 429 + Retry-After, drain finishes in-flight
requests while refusing new ones, the serve.* series land in /metrics, and
the tokenizer-less checkpoint path serves prompt_ids end to end.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.serve import session as serve_session
from cake_tpu.serve.api import start_api_server
from cake_tpu.serve.engine import SingleStreamEngine
from cake_tpu.serve.scheduler import Scheduler

# eos disabled (-1 never sampled): stream lengths are deterministic, so
# every test can assert exact token counts
CFG = tiny(max_seq_len=64, eos_token_id=-1)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)


class _FakeTok:
    """Deterministic toy tokenizer: id -> letter (every decode is alnum,
    so the streaming detok emits text on every token)."""

    def decode(self, ids):
        return "".join(chr(ord("a") + (i % 26)) for i in ids)

    def encode(self, text):
        return [ord(c) - ord("a") for c in text]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(7))


@pytest.fixture(scope="module")
def tok_server(params):
    """BatchGenerator + tokenizer behind the HTTP API: 4 slots, a 2-deep
    admission queue (small on purpose — the saturation test needs it)."""
    gen = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                         settings=SamplerSettings(**GREEDY))
    sched = Scheduler(gen, queue_depth=2, request_timeout_s=120)
    sched.start(max_concurrent=4)
    srv = start_api_server(sched)
    yield srv
    srv.close()
    sched.close()


@pytest.fixture(scope="module")
def ids_server(params):
    """The tokenizer-less path: a checkpoint dir without tokenizer.json
    must still serve prompt_ids requests (token ids come back instead of
    text)."""
    gen = BatchGenerator(CFG, params, tokenizer=None,
                         settings=SamplerSettings(**GREEDY))
    sched = Scheduler(gen, queue_depth=4, request_timeout_s=120)
    sched.start(max_concurrent=2)
    srv = start_api_server(sched)
    yield srv
    srv.close()
    sched.close()


def _url(srv) -> str:
    return f"http://127.0.0.1:{srv.port}"


def _post(srv, body: dict, timeout: float = 120.0):
    req = urllib.request.Request(
        _url(srv) + "/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _post_sse(srv, body: dict, timeout: float = 120.0,
              on_event=None) -> list[dict | str]:
    body = dict(body, stream=True)
    req = urllib.request.Request(
        _url(srv) + "/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    events: list = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        for raw in r:
            raw = raw.strip()
            if not raw.startswith(b"data: "):
                continue
            data = raw[len(b"data: "):]
            ev = data.decode() if data == b"[DONE]" else json.loads(data)
            events.append(ev)
            if on_event:
                on_event(ev)
    return events


def _ids_of(events) -> list[int]:
    return [e["token"] for e in events
            if isinstance(e, dict) and "token" in e]


def _done_of(events) -> dict:
    done = [e for e in events if isinstance(e, dict) and e.get("done")]
    assert len(done) == 1, f"expected one terminal event, got {events}"
    return done[0]


def _text_of(events) -> str:
    parts = [e["text"] for e in events
             if isinstance(e, dict) and "token" in e and e["text"]]
    tail = _done_of(events).get("text")
    return "".join(parts) + (tail or "")


PROMPTS = ["abcd", "bcde", "cdef", "defg"]


def test_concurrent_sse_clients_match_solo_runs(tok_server):
    """≥4 concurrent SSE clients stream to completion, each with exactly
    the tokens/text its prompt yields when served alone — the engine's
    batch-composition invariance, observed through the full HTTP plane."""
    solo = {}
    for p in PROMPTS:  # sequential solo runs: the reference streams
        ev = _post_sse(tok_server, {"prompt": p, "max_tokens": 8})
        solo[p] = (_ids_of(ev), _text_of(ev))
        assert len(solo[p][0]) == 8
        assert _done_of(ev)["finish_reason"] == "length"

    results: dict[str, list] = {}

    def client(p: str) -> None:
        results[p] = _post_sse(tok_server, {"prompt": p, "max_tokens": 8})

    threads = [threading.Thread(target=client, args=(p,)) for p in PROMPTS]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for p in PROMPTS:
        assert _ids_of(results[p]) == solo[p][0], f"stream for {p!r} diverged"
        assert _text_of(results[p]) == solo[p][1]
        usage = _done_of(results[p])["usage"]
        assert usage["completion_tokens"] == 8
        assert usage["ttft_ms"] > 0


def test_mid_run_arrival_admitted_without_stalling(tok_server):
    """Continuous batching through HTTP: while two long streams run, a
    late arrival is admitted and completes BEFORE they finish — and their
    token streams are unperturbed by the admission."""
    long_events: dict[str, list] = {"a": [], "b": []}
    started = threading.Event()
    counts = {"a": 0, "b": 0}

    def long_client(key: str) -> None:
        def on_event(ev):
            if isinstance(ev, dict) and "token" in ev:
                counts[key] += 1
                if counts["a"] >= 2 and counts["b"] >= 2:
                    started.set()
        long_events[key] = _post_sse(
            tok_server, {"prompt": "abab", "max_tokens": 40},
            on_event=on_event)

    threads = [threading.Thread(target=long_client, args=(k,))
               for k in ("a", "b")]
    for t in threads:
        t.start()
    assert started.wait(timeout=60), "long streams never started"
    # the arrival: admitted into a free slot while both streams decode
    out = _post(tok_server, {"prompt": "zzzz", "max_tokens": 4})
    assert out["usage"]["completion_tokens"] == 4
    # it finished while the long streams were still mid-flight
    assert counts["a"] < 40 and counts["b"] < 40
    for t in threads:
        t.join(timeout=120)
    assert len(_ids_of(long_events["a"])) == 40
    assert _ids_of(long_events["a"]) == _ids_of(long_events["b"])


def test_saturation_yields_429_with_retry_after(tok_server):
    """4 slots live + 2 queued = saturated: the next submit answers 429
    with an observed-throughput Retry-After, and never blocks the accept
    loop (serve.rejected moves)."""
    rejected0 = serve_session.REJECTED.value
    live = threading.Event()
    seen = [0, 0, 0, 0]
    results: list = [None] * 6

    def long_client(i: int) -> None:
        def on_event(ev):
            if isinstance(ev, dict) and "token" in ev:
                seen[i] += 1
                if all(n >= 1 for n in seen):
                    live.set()
        results[i] = _post_sse(
            tok_server, {"prompt": "abcd", "max_tokens": 48},
            on_event=on_event)

    threads = [threading.Thread(target=long_client, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    assert live.wait(timeout=60), "slots never filled"

    def queued_client(i: int) -> None:
        results[i] = _post(tok_server, {"prompt": "dcba", "max_tokens": 2})

    qthreads = [threading.Thread(target=queued_client, args=(i,))
                for i in (4, 5)]
    for t in qthreads:
        t.start()
    # wait until both actually sit in the admission queue
    deadline = time.time() + 30
    while time.time() < deadline:
        st = json.loads(urllib.request.urlopen(
            _url(tok_server) + "/healthz", timeout=10).read())
        if st["queued"] >= 2:
            break
        time.sleep(0.02)
    assert st["queued"] >= 2, f"queue never filled: {st}"

    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(tok_server, {"prompt": "aaaa", "max_tokens": 2})
    assert exc.value.code == 429
    assert int(exc.value.headers["Retry-After"]) >= 1
    assert serve_session.REJECTED.value > rejected0

    for t in threads + qthreads:
        t.join(timeout=180)
    assert all(len(_ids_of(r)) == 48 for r in results[:4])
    assert all(r["usage"]["completion_tokens"] == 2 for r in results[4:])


def test_disconnected_client_frees_slot(tok_server):
    """A client that walks away mid-stream must not pin its slot: the
    write failure cancels the session, finish() retires the stream (KV row
    back to the admission pool), serve.cancelled moves, and the next
    request is served."""
    cancelled0 = serve_session.CANCELLED.value
    body = json.dumps({"prompt": "abcd", "max_tokens": 56,
                       "stream": True}).encode()
    s = socket.create_connection(("127.0.0.1", tok_server.port), timeout=30)
    s.sendall(b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
              b"Content-Type: application/json\r\n"
              b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n"
              + body)
    buf = b""
    while buf.count(b"data: ") < 2:  # two token events, then vanish
        chunk = s.recv(4096)
        assert chunk, "server closed early"
        buf += chunk
    s.close()

    deadline = time.time() + 30
    while time.time() < deadline:
        if serve_session.CANCELLED.value > cancelled0:
            status = json.loads(urllib.request.urlopen(
                _url(tok_server) + "/", timeout=10).read())
            eng = status["scheduler"]["engine"]
            if eng["streams_live"] == 0:
                break
        time.sleep(0.05)
    assert serve_session.CANCELLED.value > cancelled0, "no cancellation seen"
    assert eng["streams_live"] == 0, f"slot still live: {eng}"
    # the freed slot serves the next request
    out = _post(tok_server, {"prompt": "abcd", "max_tokens": 3})
    assert out["usage"]["completion_tokens"] == 3


def test_sampler_knobs_must_match_server(tok_server):
    """The engine compiles ONE sampler; a mismatched per-request knob is
    refused loudly (400) instead of silently ignored, a matching one is
    accepted."""
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(tok_server, {"prompt": "abcd", "max_tokens": 2,
                           "temperature": 0.9})
    assert exc.value.code == 400
    assert "temperature" in json.loads(exc.value.read())["error"]
    out = _post(tok_server, {"prompt": "abcd", "max_tokens": 2,
                             "temperature": 0.0})
    assert out["usage"]["completion_tokens"] == 2


def test_serve_metrics_on_shared_port(tok_server):
    """One port serves traffic AND observability: /metrics carries the
    serve.* series in Prometheus text, / the JSON status embedding the
    registry, /healthz and /v1/models answer."""
    text = urllib.request.urlopen(
        _url(tok_server) + "/metrics", timeout=10).read().decode()
    for series in ("cake_serve_ttft_ms", "cake_serve_tpot_ms",
                   "cake_serve_queue_depth", "cake_serve_rejected",
                   "cake_serve_cancelled"):
        assert series in text, f"{series} missing from /metrics"
    status = json.loads(urllib.request.urlopen(
        _url(tok_server) + "/", timeout=10).read())
    assert status["role"] == "serve"
    assert "serve.ttft_ms" in status["metrics"]
    assert status["metrics"]["serve.ttft_ms"]["count"] > 0
    models = json.loads(urllib.request.urlopen(
        _url(tok_server) + "/v1/models", timeout=10).read())
    assert models["data"][0]["max_concurrent"] == 4
    health = json.loads(urllib.request.urlopen(
        _url(tok_server) + "/healthz", timeout=10).read())
    assert health["ok"] is True


def test_status_surface_byte_identical_with_statusd(tok_server):
    """The API server's / + /metrics must stay byte-identical with a
    standalone obs.statusd page over the same status_fn — both build
    through statusd.status_response (the factoring this test pins)."""
    from cake_tpu.obs import statusd
    from cake_tpu.serve.api import ApiServer

    def fixed_status():
        return {"role": "parity", "n": 42}

    httpd, port = statusd.start_status_server(fixed_status)
    api = ApiServer(tok_server.scheduler, status_fn=fixed_status).start()
    try:
        for path in ("/", "/metrics"):
            a = urllib.request.urlopen(
                f"http://127.0.0.1:{api.port}{path}", timeout=10)
            b = urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10)
            body_a, body_b = a.read(), b.read()
            assert body_a == body_b, f"{path} bodies diverge"
            assert (a.headers["Content-Type"]
                    == b.headers["Content-Type"])
    finally:
        api.close()
        httpd.shutdown()
        httpd.server_close()


def test_prompt_ids_serving_without_tokenizer(ids_server):
    """A checkpoint without tokenizer.json still serves: prompt_ids in,
    token ids out (no text field), both unary and SSE; a text prompt is
    refused with a clear 400."""
    out = _post(ids_server, {"prompt_ids": [1, 5, 9, 2], "max_tokens": 5})
    assert len(out["token_ids"]) == 5
    assert "text" not in out
    ev = _post_sse(ids_server,
                   {"prompt_ids": [1, 5, 9, 2], "max_tokens": 5})
    assert _ids_of(ev) == out["token_ids"]
    assert all(e["text"] is None for e in ev
               if isinstance(e, dict) and "token" in e)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(ids_server, {"prompt": "hello", "max_tokens": 2})
    assert exc.value.code == 400
    assert "tokenizer" in json.loads(exc.value.read())["error"]


def test_single_stream_engine_drain(params):
    """The one-slot adapter (the --topology serve path) + graceful drain:
    an in-flight stream runs to completion through a drain, a submit
    during the drain answers 503, and the engine thread parks."""
    gen = LlamaGenerator(CFG, params, settings=SamplerSettings(**GREEDY))
    engine = SingleStreamEngine(gen)
    sched = Scheduler(engine, queue_depth=2, request_timeout_s=60)
    sched.start(max_concurrent=1)
    assert sched.max_concurrent == 1  # the adapter serializes
    srv = start_api_server(sched)
    try:
        events: list = []
        got_two = threading.Event()

        def on_event(ev):
            if isinstance(ev, dict) and "token" in ev:
                if len([e for e in events if "token" in e]) >= 1:
                    got_two.set()
            events.append(ev)

        t = threading.Thread(target=lambda: _post_sse(
            srv, {"prompt_ids": [1, 5, 9], "max_tokens": 12},
            on_event=on_event))
        t.start()
        assert got_two.wait(timeout=60)

        drainer = threading.Thread(
            target=lambda: sched.stop(drain=True, timeout_s=60))
        drainer.start()
        # new work is refused while the in-flight stream keeps going
        deadline = time.time() + 10
        code = None
        while time.time() < deadline and code != 503:
            try:
                _post(srv, {"prompt_ids": [2, 4], "max_tokens": 2},
                      timeout=10)
            except urllib.error.HTTPError as e:
                code = e.code
        assert code == 503
        t.join(timeout=60)
        drainer.join(timeout=60)
        done = [e for e in events if isinstance(e, dict) and e.get("done")]
        assert done and done[0]["usage"]["completion_tokens"] == 12
        assert not sched._thread.is_alive()
    finally:
        srv.close()
        sched.close()


def test_engine_fault_stops_accepting(params):
    """A dead engine must refuse work, not queue it forever: an engine
    fault aborts every in-flight session with an error event, flips the
    scheduler to draining (submit -> Draining, /healthz -> 503), and the
    queue cannot grow behind a thread that will never serve it."""
    from cake_tpu.serve.scheduler import Draining
    from cake_tpu.serve.session import Session

    class BoomEngine:
        config = CFG
        tokenizer = None
        settings = SamplerSettings(**GREEDY)
        max_seq = 64

        def __init__(self):
            from cake_tpu.serve.engine import _Slot

            self.streams = [_Slot(stream_id=-1, prompt=[], done=True)]

        def _encode(self, p):
            return list(p)

        def enqueue(self, ids, sid):
            pass

        def pending_admissions(self):
            return 0

        def finish(self, sid):
            return False

        def step(self):
            raise RuntimeError("boom")

        def stats(self):
            return {}

    sched = Scheduler(BoomEngine(), queue_depth=2)
    sched.start(max_concurrent=1)
    sess = Session([1], max_tokens=2)
    sched.submit(sess)  # wakes the engine thread; step() explodes
    ev = sess.events.get(timeout=30)
    assert ev[0] == "error" and ev[1] == 503
    assert "boom" in ev[2]
    deadline = time.time() + 10
    while time.time() < deadline and not sched.stats()["draining"]:
        time.sleep(0.02)
    assert sched.stats()["draining"]
    with pytest.raises(Draining):
        sched.submit(Session([1], max_tokens=2))


def test_loadgen_closed_and_open_loop(tok_server):
    """The load generator (the serve-smoke driver): closed loop completes
    every request with sane percentiles; open loop fires Poisson arrivals
    without error."""
    from cake_tpu.tools import loadgen

    stats = loadgen.run_load(_url(tok_server), 6, concurrency=3,
                             max_tokens=4, prompt_lens=[4, 8], vocab=200,
                             seed=3)
    assert stats["completed"] == 6 and stats["errors"] == 0
    assert stats["tokens"] == 24 and stats["tok_s"] > 0
    assert stats["ttft_ms"]["p50"] > 0
    stats = loadgen.run_load(_url(tok_server), 4, max_tokens=3, rate=50.0,
                             prompt_lens=[4], vocab=200, seed=4)
    assert stats["completed"] == 4 and stats["errors"] == 0
