"""Model families beyond Llama: Mistral (sliding window), Qwen2 (q/k/v
bias), Mixtral (MoE).

The reference serves exactly one family through its Generator seam
(`model/mod.rs:21-29`, llama.rs); these tests prove the same functional
decoder serves the other families' architectural deltas, each anchored
golden against HF transformers (the strongest offline oracle, SURVEY.md §4).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cake_tpu.models import llama  # noqa: E402
from cake_tpu.models.config import LlamaConfig, tiny, tiny_moe  # noqa: E402
from cake_tpu.ops.kvcache import init_cache  # noqa: E402
from cake_tpu.utils.weights import (  # noqa: E402
    load_llama_params,
    params_from_hf_tensors,
    save_llama_params,
)

IDS = [5, 17, 42, 99, 7, 3, 88, 120]


def _port(model, cfg):
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    return params_from_hf_tensors(
        sd.__getitem__, cfg.num_hidden_layers, dtype="float32",
        num_experts=cfg.num_local_experts, attention_bias=cfg.attention_bias,
        tie_word_embeddings=cfg.tie_word_embeddings,
    )


def _parity_prefill_then_decode(model, cfg, rtol=2e-4, atol=2e-4):
    """Prefill 4 tokens then decode the rest incrementally; every step's
    logits must match the full-context HF forward at that position."""
    params = _port(model, cfg)
    with torch.no_grad():
        ref_all = model(torch.tensor([IDS])).logits[0].numpy()
    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
    logits, cache = llama.forward(
        params, jnp.asarray([IDS[:4]], jnp.int32), cache, 0, cfg
    )
    np.testing.assert_allclose(np.asarray(logits[0]), ref_all[3],
                               rtol=rtol, atol=atol)
    for i in range(4, len(IDS)):
        logits, cache = llama.forward(
            params, jnp.asarray([[IDS[i]]], jnp.int32), cache, i, cfg
        )
        np.testing.assert_allclose(np.asarray(logits[0]), ref_all[i],
                                   rtol=rtol, atol=atol)


def test_mistral_sliding_window_parity():
    # window=4 < len(IDS)=8 so the window genuinely narrows the mask
    hf_cfg = transformers.MistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        sliding_window=4, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig.from_hf_dict(hf_cfg.to_dict(), dtype="float32",
                                   max_seq_len=128)
    assert cfg.model_type == "mistral" and cfg.sliding_window == 4
    _parity_prefill_then_decode(model, cfg)


def test_mistral_window_differs_from_full():
    """The window must actually change the math (guards against a mask
    that silently degrades to full causal)."""
    hf_cfg = transformers.MistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, sliding_window=4,
    )
    torch.manual_seed(0)
    model = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig.from_hf_dict(hf_cfg.to_dict(), dtype="float32",
                                   max_seq_len=128)
    params = _port(model, cfg)
    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
    win, _ = llama.forward(params, jnp.asarray([IDS], jnp.int32), cache, 0, cfg)
    import dataclasses

    full_cfg = dataclasses.replace(cfg, sliding_window=None)
    cache = init_cache(full_cfg, batch=1, max_seq=cfg.max_seq_len)
    full, _ = llama.forward(params, jnp.asarray([IDS], jnp.int32), cache, 0,
                            full_cfg)
    assert float(jnp.abs(win - full).max()) > 1e-3


def test_qwen2_bias_parity():
    hf_cfg = transformers.Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    # HF zero-inits projection biases; randomize them so the bias path is
    # genuinely exercised (a loader that dropped them would still "match"
    # against zeros)
    with torch.no_grad():
        for name, p in model.named_parameters():
            if name.endswith("proj.bias"):
                p.normal_(0.0, 0.1)
    cfg = LlamaConfig.from_hf_dict(hf_cfg.to_dict(), dtype="float32",
                                   max_seq_len=128)
    # Qwen2's q/k/v bias is implied by the family, not spelled in the config
    assert cfg.model_type == "qwen2" and cfg.attention_bias
    assert float(model.state_dict()["model.layers.0.self_attn.q_proj.bias"]
                 .abs().max()) > 0
    _parity_prefill_then_decode(model, cfg)


def test_mixtral_moe_parity():
    hf_cfg = transformers.MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        num_local_experts=4, num_experts_per_tok=2,
        sliding_window=None, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.MixtralForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig.from_hf_dict(hf_cfg.to_dict(), dtype="float32",
                                   max_seq_len=128)
    assert cfg.num_local_experts == 4 and cfg.num_experts_per_tok == 2
    # prefill (dense-dispatch path: N*k > GATHER_MAX_ROWS) and incremental
    # decode (gather path: N=1) both run against the same HF oracle
    _parity_prefill_then_decode(model, cfg)


def test_family_checkpoint_round_trip(tmp_path):
    """save -> load through the real safetensors path for a biased MoE
    params pytree (both family extensions at once)."""
    cfg = tiny_moe(attention_bias=True)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    save_llama_params(params, tmp_path, cfg.num_hidden_layers)
    loaded = load_llama_params(
        tmp_path, cfg.num_hidden_layers, dtype="float32",
        num_experts=cfg.num_local_experts, attention_bias=True,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=0, atol=0),
        params, loaded,
    )


def test_moe_int4_load_rejected_int8_loads(tmp_path):
    """int4 expert packing is not wired (rejected loudly); int8 expert
    stacks load and match quantize_params applied to the host pytree."""
    from cake_tpu.ops.quant import quantize_params

    cfg = tiny_moe()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    save_llama_params(params, tmp_path, cfg.num_hidden_layers)
    with pytest.raises(NotImplementedError, match="int4"):
        load_llama_params(tmp_path, cfg.num_hidden_layers, quantize="int4",
                          num_experts=cfg.num_local_experts)
    loaded = load_llama_params(tmp_path, cfg.num_hidden_layers,
                               dtype="float32", quantize="int8")
    want = quantize_params(params, bits=8)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        loaded, want,
    )


def test_config_family_round_trip():
    for make in (lambda: tiny(model_type="mistral", sliding_window=4),
                 lambda: tiny(model_type="qwen2", attention_bias=True),
                 lambda: tiny_moe()):
        cfg = make()
        again = LlamaConfig.from_hf_dict(cfg.to_hf_dict(), dtype=cfg.dtype,
                                         max_seq_len=cfg.max_seq_len)
        assert again == cfg


def test_family_sharded_load_matches_host_load(tmp_path):
    """Direct-to-mesh loading of a biased MoE checkpoint (family tensors
    auto-detected from the stored names) equals host-load + shard_params,
    with the expert axis genuinely sharded over ep."""
    from cake_tpu.parallel.mesh import EP, MeshPlan, shard_params

    cfg = tiny_moe(attention_bias=True)
    params = llama.init_params(cfg, jax.random.PRNGKey(3))
    save_llama_params(params, tmp_path, cfg.num_hidden_layers)

    from cake_tpu.utils.sharded_load import load_llama_params_on_mesh

    plan = MeshPlan.build(cfg, num_stages=2, ep=2)
    got = load_llama_params_on_mesh(tmp_path, cfg, plan.mesh)
    want = shard_params(
        load_llama_params(tmp_path, cfg.num_hidden_layers, dtype="float32"),
        plan.mesh,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        got, want,
    )
    # the expert stacks are actually ep-sharded, not replicated
    spec = got["layers"]["w_gate"].sharding.spec
    assert EP in spec, spec


def test_llama_arch_attention_bias_parity():
    """HF llama-arch `attention_bias: true` biases q/k/v AND o_proj; the
    o_proj bias must load and apply (review finding: silently dropped)."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=True, mlp_bias=False, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for name, p in model.named_parameters():
            if name.endswith("proj.bias"):
                p.normal_(0.0, 0.1)
    assert float(model.state_dict()["model.layers.0.self_attn.o_proj.bias"]
                 .abs().max()) > 0
    cfg = LlamaConfig.from_hf_dict(hf_cfg.to_dict(), dtype="float32",
                                   max_seq_len=128)
    assert cfg.attention_bias
    # port through the auto-detecting checkpoint path so bo is exercised
    params = _port_o(model, cfg)
    with torch.no_grad():
        ref_all = model(torch.tensor([IDS])).logits[0].numpy()
    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
    logits, cache = llama.forward(
        params, jnp.asarray([IDS[:4]], jnp.int32), cache, 0, cfg
    )
    np.testing.assert_allclose(np.asarray(logits[0]), ref_all[3],
                               rtol=2e-4, atol=2e-4)


def _port_o(model, cfg):
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    return params_from_hf_tensors(
        sd.__getitem__, cfg.num_hidden_layers, dtype="float32",
        attention_bias=True, o_bias=True,
        tie_word_embeddings=cfg.tie_word_embeddings,
    )


def test_qwen2_partial_window_rejected():
    """A use_sliding_window=true config with a partial max_window_layers
    depth must be rejected, not silently served with a uniform window."""
    d = tiny().to_hf_dict()
    d.update(model_type="qwen2", sliding_window=4, use_sliding_window=True,
             max_window_layers=2)
    with pytest.raises(ValueError, match="max_window_layers"):
        LlamaConfig.from_hf_dict(d)
    # gated off -> no window regardless of the value
    d.update(use_sliding_window=False)
    assert LlamaConfig.from_hf_dict(d).sliding_window is None
    # full depth (0) -> uniform window, supported
    d.update(use_sliding_window=True, max_window_layers=0)
    assert LlamaConfig.from_hf_dict(d).sliding_window == 4


def test_quantize_model_moe_int8_round_trip(tmp_path):
    """Offline int8 pre-quantization of an MoE checkpoint: expert tensors
    get .q8/.scale, the pre-quantized load is bit-equal to quantize-on-load,
    and int4 is rejected up front."""
    from cake_tpu.tools.quantize_model import quantize_checkpoint

    cfg = tiny_moe()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    save_llama_params(params, tmp_path / "src", cfg.num_hidden_layers)
    with pytest.raises(NotImplementedError, match="int4"):
        quantize_checkpoint(tmp_path / "src", tmp_path / "q4", bits=4)
    out = quantize_checkpoint(tmp_path / "src", tmp_path / "q8", bits=8)
    pre = load_llama_params(out, cfg.num_hidden_layers, dtype="float32",
                            quantize="int8")
    onfly = load_llama_params(tmp_path / "src", cfg.num_hidden_layers,
                              dtype="float32", quantize="int8")
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        pre, onfly,
    )


def test_family_sharded_load_int8_moe_matches_host(tmp_path):
    """Direct-to-mesh int8 MoE: quantize-on-load expert stacks (and a
    pre-quantized .q8 checkpoint) equal host-load + shard_params bit for
    bit, with the expert q/scale leaves genuinely ep-sharded."""
    from cake_tpu.parallel.mesh import EP, MeshPlan, shard_params
    from cake_tpu.tools.quantize_model import quantize_checkpoint
    from cake_tpu.utils.sharded_load import load_llama_params_on_mesh

    cfg = tiny_moe()
    params = llama.init_params(cfg, jax.random.PRNGKey(7))
    save_llama_params(params, tmp_path / "src", cfg.num_hidden_layers)
    # tp=2 exercises the expert callbacks' SLICED reads: column-parallel
    # w_gate/w_up quantize a column slice, row-parallel w_down reads its
    # row shard against the memoized full-in-axis scale
    plan = MeshPlan.build(cfg, num_stages=2, ep=2, tp=2)

    want = shard_params(
        load_llama_params(tmp_path / "src", cfg.num_hidden_layers,
                          dtype="float32", quantize="int8"),
        plan.mesh,
    )
    got = load_llama_params_on_mesh(tmp_path / "src", cfg, plan.mesh,
                                    quantize="int8")
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        got, want,
    )
    assert EP in got["layers"]["w_gate"].q.sharding.spec
    assert EP in got["layers"]["w_down"].scale.sharding.spec

    # pre-quantized .q8 checkpoint through the same path
    out = quantize_checkpoint(tmp_path / "src", tmp_path / "q8", bits=8)
    pre = load_llama_params_on_mesh(out, cfg, plan.mesh, quantize="int8")
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        pre, want,
    )


def test_gemma_parity():
    """Gemma: explicit head_dim (heads x head_dim != hidden), GeGLU,
    (1+w) RMSNorm, sqrt(hidden)-scaled embeddings, tied head — the
    structurally different family, held to the same HF golden bar."""
    hf_cfg = transformers.GemmaConfig(
        vocab_size=256, hidden_size=48, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16,  # 4 x 16 = 64 != hidden 48
        max_position_embeddings=128, rms_norm_eps=1e-6, rope_theta=10000.0,
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=True,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.GemmaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig.from_hf_dict(hf_cfg.to_dict(), dtype="float32",
                                   max_seq_len=128)
    assert cfg.model_type == "gemma"
    assert cfg.head_dim == 16 and cfg.hidden_act == "gelu_tanh"
    assert cfg.rms_norm_offset and cfg.embed_scale and cfg.tie_word_embeddings
    _parity_prefill_then_decode(model, cfg)


def test_gemma_config_round_trip():
    from cake_tpu.models.config import gemma_7b

    cfg = gemma_7b(max_seq_len=64)
    again = LlamaConfig.from_hf_dict(cfg.to_hf_dict(), dtype=cfg.dtype,
                                     max_seq_len=64)
    assert again == cfg
    # a non-default head_dim survives the round trip explicitly
    assert again.head_dim == 256


def test_gemma_mesh_parity():
    """Gemma over the mesh pipeline (stage x tp): token-identical to the
    all-local stream — the embed scaling / norm offset / GeGLU deltas ride
    the one shared code path, so sharding cannot diverge from local."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator
    from cake_tpu.runtime.mesh_generator import MeshGenerator

    cfg = tiny(model_type="gemma", hidden_act="gelu_tanh",
               rms_norm_offset=True, embed_scale=True, head_dim=8,
               max_seq_len=64)
    assert cfg.head_dim == 8  # explicit, != hidden/heads = 16
    params = llama.init_params(cfg, jax.random.PRNGKey(11))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    ref = LlamaGenerator(cfg, params, settings=settings)
    ref.set_prompt([5, 9, 2, 11])
    want = [ref.next_token(i).id for i in range(6)]

    g = MeshGenerator(cfg, params, settings=settings, num_stages=2, tp=2)
    g.set_prompt([5, 9, 2, 11])
    assert [g.next_token(i).id for i in range(6)] == want


def test_tied_head_auto_detected(tmp_path):
    """A checkpoint with no stored lm_head.weight (Gemma/Llama-3.2-1B
    style) can only be tied — both loaders must detect that instead of
    KeyError-ing when a call site forgets the flag (CLI repro)."""
    from safetensors.numpy import save_file

    from cake_tpu.parallel.mesh import MeshPlan
    from cake_tpu.utils.sharded_load import load_llama_params_on_mesh
    from cake_tpu.utils.weights import _LAYER_MAP

    cfg = tiny(model_type="gemma", hidden_act="gelu_tanh",
               rms_norm_offset=True, embed_scale=True, head_dim=8,
               max_seq_len=64, tie_word_embeddings=True)
    p = llama.init_params(cfg, jax.random.PRNGKey(2))
    tensors = {
        "model.embed_tokens.weight": np.asarray(p["embed"], np.float32),
        "model.norm.weight": np.asarray(p["norm_f"], np.float32),
    }
    for ours, (suffix, transpose) in _LAYER_MAP.items():
        st = np.asarray(p["layers"][ours], np.float32)
        for i in range(cfg.num_hidden_layers):
            w = st[i]
            tensors[f"model.layers.{i}.{suffix}"] = np.ascontiguousarray(
                w.T if transpose else w)
    save_file(tensors, tmp_path / "model.safetensors")
    (tmp_path / "model.safetensors.index.json").write_text(
        __import__("json").dumps({"metadata": {"total_size": 0},
                                  "weight_map": {k: "model.safetensors"
                                                 for k in tensors}}))

    # the flag is NOT passed: detection must kick in on both loaders
    host = load_llama_params(tmp_path, cfg.num_hidden_layers,
                             dtype="float32")
    np.testing.assert_array_equal(np.asarray(host["lm_head"]),
                                  np.asarray(host["embed"]).T)
    plan = MeshPlan.build(cfg, num_stages=2, tp=2)
    mesh_p = load_llama_params_on_mesh(tmp_path, cfg, plan.mesh)
    # head_dim != hidden//heads flows through the mesh loader's shapes
    assert mesh_p["layers"]["wq"].shape == (
        cfg.num_hidden_layers, cfg.hidden_size,
        cfg.num_attention_heads * 8)
    np.testing.assert_array_equal(np.asarray(mesh_p["lm_head"]),
                                  np.asarray(host["lm_head"]))


def test_gemma_distributed_worker_parity():
    """The TCP master/worker path must apply the Gemma embed scaling too
    (review repro: the master's raw embed lookup skipped it)."""
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedGenerator, build_runners
    from cake_tpu.runtime.worker import Worker
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    cfg = tiny(model_type="gemma", hidden_act="gelu_tanh",
               rms_norm_offset=True, embed_scale=True, head_dim=8,
               max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(4))

    def loader(lo, hi):
        return jax.tree.map(lambda a: a[lo:hi], params["layers"])

    w = Worker("w", cfg,
               Topology.from_dict({"w": {"layers": ["model.layers.2-3"]}}),
               loader, address="127.0.0.1:0", max_seq=cfg.max_seq_len)
    w.serve_in_background()
    try:
        topo = Topology.from_dict({
            "w": {"host": f"127.0.0.1:{w.port}",
                  "layers": ["model.layers.2-3"]},
        })
        settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
        runners = build_runners(cfg, topo, loader)
        head = {k: params[k] for k in ("embed", "norm_f", "lm_head")}
        g = DistributedGenerator(cfg, head, runners, settings=settings)
        g.set_prompt([5, 9, 2])
        got = [g.next_token(i).id for i in range(6)]
        ref = LlamaGenerator(cfg, params, settings=settings)
        ref.set_prompt([5, 9, 2])
        assert got == [ref.next_token(i).id for i in range(6)]
        g.close()
    finally:
        w.shutdown()


def test_prequantized_untied_head_not_falsely_tied(tmp_path):
    """Pre-quantized untied checkpoints store the head as
    lm_head.weight.q8 — the tied-head probe must count that as a stored
    head (review repro: it falsely tied and served embedding logits)."""
    from cake_tpu.ops.quant import quantize_params
    from cake_tpu.tools.quantize_model import quantize_checkpoint

    cfg = tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(6))
    save_llama_params(params, tmp_path / "src", cfg.num_hidden_layers)
    out = quantize_checkpoint(tmp_path / "src", tmp_path / "q8", bits=8)
    loaded = load_llama_params(out, cfg.num_hidden_layers, dtype="float32",
                               quantize="int8")
    want = quantize_params(params, bits=8)
    np.testing.assert_array_equal(np.asarray(loaded["lm_head"].q),
                                  np.asarray(want["lm_head"].q))


def test_mistral_serving_batch_generator_parity():
    """Sliding-window family through the multi-stream serving plane
    (per-row frontiers use the windowed per-row XLA mask): every stream
    reproduces its solo run token for token."""
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    cfg = tiny(model_type="mistral", sliding_window=8, max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(9))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    prompts = [[5, 9, 2, 11, 4, 3, 8, 7, 1, 2], [3, 1, 4, 1], [7, 7, 2]]

    solo = []
    for p in prompts:
        g = LlamaGenerator(cfg, params, settings=settings)
        g.set_prompt(p)
        solo.append([g.next_token(i).id for i in range(12)])

    bg = BatchGenerator(cfg, params, settings=settings, num_stages=2,
                        block_size=2)
    bg.set_prompts(prompts)
    outs = bg.generate(12)
    assert [list(o) for o in outs] == solo


def test_mistral_int8_kv_window_composition():
    """Sliding window x int8 KV cache, with real oracles:

    - a window WIDER than everything the stream ever attends must be a
      no-op — stream identical to the unwindowed config on the same
      quantized cache (the sharp equality: the windowed code path
      degenerates exactly);
    - the narrow window must actually change the stream (the mask is not
      silently dropped on the dequant path)."""
    import dataclasses

    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    prompt = [5, 9, 2, 11, 4, 3, 8, 7, 1, 2]
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)

    def stream(cfg):
        g = LlamaGenerator(cfg, params, settings=settings, kv_quant="int8")
        g.set_prompt(prompt)
        return [g.next_token(i).id for i in range(10)]

    base = tiny(model_type="mistral", sliding_window=None, max_seq_len=64)
    params = llama.init_params(base, jax.random.PRNGKey(10))
    unwindowed = stream(base)
    wide = stream(dataclasses.replace(base, sliding_window=1000))
    assert wide == unwindowed  # window >= history: exact degeneration
    narrow = stream(dataclasses.replace(base, sliding_window=4))
    assert narrow != unwindowed  # the mask genuinely applies
