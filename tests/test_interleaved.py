"""Interleaved-microbatch serving decode (parallel/pipeline.py
build_interleaved_decode): the decode twin of pipelined prefill.

Contrast anchor (SURVEY.md §2): the reference's pipeline — and the plain
staged decode here — keeps upstream workers idle (inactive stages compute
into a discarded select) for every token. The interleaved schedule
round-robins the dp batch's S microbatches over the S stages so every
stage does useful layer work every cycle. The contract proven here:

1. emitted streams, cache contents, and sampler state are BIT-IDENTICAL
   to the serialized per-row decode (same keys, positions, history);
2. wall-clock on the shared-core virtual mesh improves by ~the S× less
   per-cycle layer work (cores are shared between the virtual devices, so
   the measured ratio is a damped proxy of the real-mesh scaling);
3. BatchGenerator picks the schedule automatically and falls back to the
   serialized program when the batch does not divide by the stage count.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.config import tiny
from cake_tpu.models.llama import init_params
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import (
    MeshPlan,
    init_cache_on_mesh,
    shard_params,
)
from cake_tpu.parallel.pipeline import (
    build_interleaved_decode,
    build_sharded_decode,
    build_sharded_prefill,
)


def _cfg(**kw):
    base = dict(max_seq_len=64, num_hidden_layers=8, hidden_size=64,
                intermediate_size=128, num_attention_heads=8,
                num_key_value_heads=4, vocab_size=96, dtype="bfloat16")
    base.update(kw)
    return tiny(**base)


def _run_decode(cfg, plan, params, build, batch, steps, settings,
                kv_quant=None, **kw):
    p = shard_params(params, plan.mesh)
    cache = init_cache_on_mesh(cfg, plan.mesh, batch=batch,
                               max_seq=cfg.max_seq_len, quant=kv_quant)
    prefill = build_sharded_prefill(cfg, plan, params_like=p,
                                    kv_quant=kv_quant)
    prompt = jnp.asarray([[1, 5, 9, 14, 3, 8, 2, 4]] * batch, jnp.int32)
    logits, cache = prefill(p, prompt, cache,
                            jnp.full((batch,), 7, jnp.int32))
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    keys = jnp.stack([jax.random.fold_in(jax.random.PRNGKey(0), i)
                      for i in range(batch)])
    pos = jnp.full((batch,), 8, jnp.int32)
    hist = jnp.full((batch, 16), -1, jnp.int32)
    slot = jnp.zeros((batch,), jnp.int32)
    idx = jnp.ones((batch,), jnp.int32)
    dec = build(cfg, settings, plan, params_like=p, steps=steps,
                kv_quant=kv_quant, **kw)
    toks, cache, hist, slot = dec(p, tok, cache, pos, keys, hist, slot, idx)
    flat = [np.asarray(x) for x in jax.tree.leaves(cache)]
    return np.asarray(toks), flat, np.asarray(hist), np.asarray(slot)


@pytest.mark.parametrize("mesh_kw,batch", [
    (dict(num_stages=4, tp=1, dp=1), 8),
    (dict(num_stages=2, tp=2, dp=2), 8),
    (dict(num_stages=2, tp=1, dp=1), 2),  # microbatch of one row
])
def test_bit_identical_to_serialized(mesh_kw, batch):
    """Sampled streams + cache + sampler state match the serialized
    per-row program exactly, across pipeline/tp/dp layouts."""
    cfg = _cfg()
    n = mesh_kw["num_stages"] * mesh_kw["tp"] * mesh_kw["dp"]
    plan = MeshPlan.build(cfg, devices=jax.devices()[:n], **mesh_kw)
    params = init_params(cfg, jax.random.PRNGKey(0))
    settings = SamplerSettings(temperature=0.9, top_k=20,
                               repeat_penalty=1.1)
    t1, c1, h1, s1 = _run_decode(
        cfg, plan, params, build_sharded_decode, batch, 4, settings,
        per_row=True)
    t2, c2, h2, s2 = _run_decode(
        cfg, plan, params, build_interleaved_decode, batch, 4, settings)
    np.testing.assert_array_equal(t1, t2)
    for a, b in zip(c1, c2):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(h1, h2)
    np.testing.assert_array_equal(s1, s2)


def test_bit_identical_int8_kv():
    """The quantize-on-write KV tier composes with the interleaved
    schedule (row-sliced QuantizedKV buffers round-trip exactly)."""
    cfg = _cfg()
    plan = MeshPlan.build(cfg, num_stages=4, devices=jax.devices()[:4])
    params = init_params(cfg, jax.random.PRNGKey(1))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    t1, c1, *_ = _run_decode(cfg, plan, params, build_sharded_decode, 8, 4,
                             settings, kv_quant="int8", per_row=True)
    t2, c2, *_ = _run_decode(cfg, plan, params, build_interleaved_decode,
                             8, 4, settings, kv_quant="int8")
    np.testing.assert_array_equal(t1, t2)
    for a, b in zip(c1, c2):
        np.testing.assert_array_equal(a, b)


def test_steps1_signature():
    """steps=1 returns [B] like the serialized per-row single-step."""
    cfg = _cfg()
    plan = MeshPlan.build(cfg, num_stages=2, devices=jax.devices()[:2])
    params = init_params(cfg, jax.random.PRNGKey(2))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    t1, *_ = _run_decode(cfg, plan, params, build_sharded_decode, 4, 1,
                         settings, per_row=True)
    t2, *_ = _run_decode(cfg, plan, params, build_interleaved_decode, 4, 1,
                         settings)
    assert t1.shape == t2.shape == (4,)
    np.testing.assert_array_equal(t1, t2)


def test_indivisible_batch_rejected():
    cfg = _cfg()
    plan = MeshPlan.build(cfg, num_stages=4, devices=jax.devices()[:4])
    params = init_params(cfg, jax.random.PRNGKey(0))
    settings = SamplerSettings()
    with pytest.raises(ValueError, match="divisible"):
        _run_decode(cfg, plan, params, build_interleaved_decode, 6, 2,
                    settings)


def test_throughput_scales_on_virtual_mesh():
    """Aggregate serving tok/s beats the serialized loop when dp-batch >=
    stages. The serialized schedule burns S× the layer FLOPs per cycle
    (every stage computes the full batch, one result kept); on the
    shared-core virtual mesh that extra work is real CPU time, so the
    interleaved program must be measurably faster. The assertion bar
    (1.25×) is far below the ideal ~S× because the virtual devices share
    host cores and per-cycle dispatch overhead is CPU-sized; the measured
    ratio at S=4/steps=8 on this config is ~1.7×."""
    cfg = _cfg(max_seq_len=256, hidden_size=256, intermediate_size=512,
               vocab_size=1024)
    S, B, steps = 4, 16, 8
    plan = MeshPlan.build(cfg, num_stages=S, devices=jax.devices()[:S])
    params = init_params(cfg, jax.random.PRNGKey(0))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    p = shard_params(params, plan.mesh)

    def timed(build, **kw):
        cache = init_cache_on_mesh(cfg, plan.mesh, batch=B, max_seq=256)
        tok = jnp.ones((B,), jnp.int32)
        keys = jnp.stack([jax.random.fold_in(jax.random.PRNGKey(0), i)
                          for i in range(B)])
        pos = jnp.full((B,), 8, jnp.int32)
        hist = jnp.full((B, 16), -1, jnp.int32)
        slot = jnp.zeros((B,), jnp.int32)
        idx = jnp.ones((B,), jnp.int32)
        dec = build(cfg, settings, plan, params_like=p, steps=steps, **kw)
        out = dec(p, tok, cache, pos, keys, hist, slot, idx)
        jax.block_until_ready(out)  # compile + warm
        toks, cache, hist, slot = out
        n, t0 = 4, time.perf_counter()
        for i in range(n):
            toks, cache, hist, slot = dec(
                p, toks[-1].astype(jnp.int32), cache, pos + steps * (i + 1),
                keys, hist, slot, idx + steps * (i + 1))
        jax.block_until_ready(toks)
        return (time.perf_counter() - t0) / n

    # best-of-3: wall-clock on the shared-core virtual mesh is sensitive
    # to concurrent load (a parallel test run dipped one sample below the
    # bar); transient contention is exactly what best-of smooths, while a
    # real regression fails all three samples
    best = 0.0
    for _ in range(3):
        t_serial = timed(build_sharded_decode, per_row=True)
        t_il = timed(build_interleaved_decode)
        best = max(best, t_serial / t_il)
        if best > 1.25:
            break
    assert best > 1.25, (
        f"interleaved {t_il * 1e3:.0f}ms/block not faster than serialized "
        f"{t_serial * 1e3:.0f}ms/block (best ratio {best:.2f} of 3 runs)"
    )


def test_batch_generator_auto_interleave():
    """BatchGenerator swaps the interleaved program in when the batch
    divides by the stage count and the streams match the serialized
    output; an indivisible batch silently uses the serialized fallback."""
    from cake_tpu.runtime.batch_generator import BatchGenerator

    cfg = _cfg(eos_token_id=-1)
    prompts = [[1, 5, 9, 2], [7, 3, 8, 1], [2, 2, 4, 4], [9, 8, 7, 6]]

    def run(interleave, n_prompts=4):
        plan = MeshPlan.build(cfg, num_stages=2, devices=jax.devices()[:2])
        gen = BatchGenerator(cfg, init_params(cfg, jax.random.PRNGKey(3)),
                             plan=plan,
                             settings=SamplerSettings(temperature=0.8,
                                                      top_k=20, seed=7),
                             block_size=2, interleave=interleave)
        gen.set_prompts([list(x) for x in prompts[:n_prompts]])
        out = [[] for _ in range(n_prompts)]
        for _ in range(6):
            for i, t in enumerate(gen.step()):
                if t is not None:
                    out[i].append(int(t.id) if hasattr(t, "id") else int(t))
        return out

    il = run(interleave=True)
    serial = run(interleave=False)
    assert il == serial
    # odd batch: the picker must fall back (still correct output)
    il3 = run(interleave=True, n_prompts=3)
    serial3 = run(interleave=False, n_prompts=3)
    assert il3 == serial3


def test_bit_identical_int8_weights_under_pin():
    """Int8 WEIGHTS (quantized linears + lm_head): streams match the
    serialized program bit-for-bit under a pinned quant backend — the
    BatchGenerator contract (it always pins before tracing). Covers the
    vocab-split head's backend-class guard."""
    from cake_tpu.ops import quant
    from cake_tpu.ops.quant import quantize_params

    cfg = _cfg(vocab_size=96)
    plan = MeshPlan.build(cfg, num_stages=4, devices=jax.devices()[:4])
    qparams = quantize_params(init_params(cfg, jax.random.PRNGKey(5)))
    settings = SamplerSettings(temperature=0.9, top_k=20, repeat_penalty=1.1)
    with quant.pinned_impl("xla"):
        t1, c1, h1, s1 = _run_decode(
            cfg, plan, qparams, build_sharded_decode, 8, 4, settings,
            per_row=True)
        t2, c2, h2, s2 = _run_decode(
            cfg, plan, qparams, build_interleaved_decode, 8, 4, settings)
    np.testing.assert_array_equal(t1, t2)
    for a, b in zip(c1, c2):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(h1, h2)


# -- interleaved verification (serving speculation on stages > 1) -------------

@pytest.mark.parametrize("mesh_kw,batch,kv_quant", [
    (dict(num_stages=4, tp=1, dp=1), 8, None),
    (dict(num_stages=2, tp=2, dp=2), 8, None),
    (dict(num_stages=4, tp=1, dp=1), 8, "int8"),  # quantized staging cache
])
def test_interleaved_verify_bit_identical(mesh_kw, batch, kv_quant):
    """build_interleaved_verify_rows: logits at every position and the KV
    writes (incl. QuantizedKV q/scale slicing) match the serialized
    per-row verify exactly."""
    from cake_tpu.parallel.pipeline import (
        build_interleaved_verify_rows,
        build_sharded_verify_rows,
    )

    cfg = _cfg()
    n = mesh_kw["num_stages"] * mesh_kw["tp"] * mesh_kw["dp"]
    plan = MeshPlan.build(cfg, devices=jax.devices()[:n], **mesh_kw)
    params = init_params(cfg, jax.random.PRNGKey(0))
    p = shard_params(params, plan.mesh)

    def run(build):
        cache = init_cache_on_mesh(cfg, plan.mesh, batch=batch, max_seq=64,
                                   quant=kv_quant)
        prefill = build_sharded_prefill(cfg, plan, params_like=p,
                                        kv_quant=kv_quant)
        prompt = jnp.asarray([[1, 5, 9, 14, 3, 8, 2, 4]] * batch, jnp.int32)
        _, cache = prefill(p, prompt, cache,
                           jnp.full((batch,), 7, jnp.int32))
        fed = jnp.asarray(
            np.random.default_rng(1).integers(1, 90, (batch, 5)), jnp.int32)
        pos = jnp.asarray([8, 9, 8, 10, 8, 9, 11, 8][:batch], jnp.int32)
        v = build(cfg, plan, params_like=p, kv_quant=kv_quant)
        logits, cache = v(p, fed, cache, pos)
        return (np.asarray(logits),
                [np.asarray(x) for x in jax.tree.leaves(cache)])

    l1, c1 = run(build_sharded_verify_rows)
    l2, c2 = run(build_interleaved_verify_rows)
    np.testing.assert_array_equal(l1, l2)
    for a, b in zip(c1, c2):
        np.testing.assert_array_equal(a, b)


def test_spec_serving_on_stages_uses_interleaved_verify():
    """BatchGenerator with spec_k on a staged mesh: the interleaved verify
    (and interleaved decode fallback) serve the rounds; streams match the
    1-stage serving oracle bit-for-bit."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    cfg = _cfg(eos_token_id=-1)
    params = init_params(cfg, jax.random.PRNGKey(3))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    prompts = [[5, 9, 2, 5, 9, 2], [3, 1, 4, 1, 3, 1]]

    flat = BatchGenerator(cfg, params, settings=settings, spec_k=4)
    flat.set_prompts([list(p) for p in prompts])
    want = flat.generate(10)

    plan = MeshPlan.build(cfg, num_stages=2, devices=jax.devices()[:2])
    staged = BatchGenerator(cfg, params, plan=plan, settings=settings,
                            spec_k=4)
    staged.set_prompts([list(p) for p in prompts])
    assert staged.generate(10) == want
    assert staged.stats()["spec_dispatches"] >= 1
    # the interleaved verify program was actually built and used
    assert staged._BatchGenerator__verify_rows_il is not None


def test_interleaved_verify_int8_weights_under_pin():
    """Int8 WEIGHTS through the interleaved verify's vocab-split head:
    logits bit-identical to the serialized verify under a pinned backend
    (the QuantizedLinear q/scale sub-head slice path)."""
    from cake_tpu.ops import quant
    from cake_tpu.ops.quant import quantize_params
    from cake_tpu.parallel.pipeline import (
        build_interleaved_verify_rows,
        build_sharded_verify_rows,
    )

    cfg = _cfg(vocab_size=96)
    plan = MeshPlan.build(cfg, num_stages=4, devices=jax.devices()[:4])
    qparams = quantize_params(init_params(cfg, jax.random.PRNGKey(6)))
    p = shard_params(qparams, plan.mesh)
    batch = 8

    def run(build):
        cache = init_cache_on_mesh(cfg, plan.mesh, batch=batch, max_seq=64)
        prefill = build_sharded_prefill(cfg, plan, params_like=p)
        prompt = jnp.asarray([[1, 5, 9, 14, 3, 8, 2, 4]] * batch, jnp.int32)
        _, cache = prefill(p, prompt, cache,
                           jnp.full((batch,), 7, jnp.int32))
        fed = jnp.asarray(
            np.random.default_rng(2).integers(1, 90, (batch, 4)), jnp.int32)
        pos = jnp.asarray([8, 9, 8, 10, 8, 9, 11, 8], jnp.int32)
        v = build(cfg, plan, params_like=p)
        logits, _ = v(p, fed, cache, pos)
        return np.asarray(logits)

    with quant.pinned_impl("xla"):
        l1 = run(build_sharded_verify_rows)
        l2 = run(build_interleaved_verify_rows)
    np.testing.assert_array_equal(l1, l2)
