"""MeshGenerator: the Generator surface over the single-program mesh
pipeline must match the all-local generator token-for-token (the same
golden-parity bar the cross-host runtime is held to in test_distributed)."""

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.runtime.mesh_generator import MeshGenerator

CFG = tiny(max_seq_len=64)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(5))


def _local_stream(params, prompt, n, settings):
    g = LlamaGenerator(CFG, params, settings=settings)
    g.set_prompt(prompt)
    return [g.next_token(i).id for i in range(n)]


@pytest.mark.parametrize(
    "axes",
    [
        dict(num_stages=2),
        dict(tp=2),
        dict(num_stages=2, tp=2),
        dict(num_stages=2, tp=2, sp=2),
    ],
    ids=lambda a: "-".join(f"{k}{v}" for k, v in a.items()),
)
def test_greedy_parity_with_local(params, axes):
    settings = SamplerSettings(**GREEDY)
    g = MeshGenerator(CFG, params, settings=settings, **axes)
    g.set_prompt([5, 9, 2, 11])
    got = [g.next_token(i).id for i in range(6)]
    assert got == _local_stream(params, [5, 9, 2, 11], 6, settings)


def test_pipelined_prefill_chunks_parity(params):
    """prefill_chunks (GPipe overlap) streams the same tokens as the plain
    staged prefill and the all-local generator."""
    settings = SamplerSettings(**GREEDY)
    g = MeshGenerator(CFG, params, settings=settings, num_stages=2, tp=2,
                      prefill_chunks=4)
    g.set_prompt([5, 9, 2, 11, 7, 3])
    got = [g.next_token(i).id for i in range(6)]
    assert got == _local_stream(params, [5, 9, 2, 11, 7, 3], 6, settings)


def test_prefill_chunks_divisibility_validated(params):
    """max_seq must divide into prefill chunks, or a max_seq-capped bucket
    would round past the cache window (clamped KV writes, silently wrong
    logits — r2 code-review regression)."""
    with pytest.raises(ValueError, match="prefill_chunks"):
        MeshGenerator(CFG, params, num_stages=2, prefill_chunks=3)
    # one stage has nothing to overlap — reject instead of running M
    # sequential chunk passes that are strictly slower
    with pytest.raises(ValueError, match="num_stages"):
        MeshGenerator(CFG, params, num_stages=1, prefill_chunks=2)


def test_second_prompt_resets_stream(params):
    settings = SamplerSettings(**GREEDY)
    g = MeshGenerator(CFG, params, settings=settings, num_stages=2, tp=2)
    g.set_prompt([3, 1, 4])
    first = [g.next_token(i).id for i in range(5)]
    g.set_prompt([3, 1, 4])
    assert [g.next_token(i).id for i in range(5)] == first
    # and a different prompt actually changes the stream
    g.set_prompt([9, 8, 7, 6, 5])
    assert [g.next_token(i).id for i in range(5)] != first


def test_dp_plan_rejected(params):
    from cake_tpu.parallel.mesh import MeshPlan

    plan = MeshPlan.build(CFG, num_stages=2, dp=2)
    with pytest.raises(ValueError, match="dp=1"):
        MeshGenerator(CFG, params, plan=plan)


@pytest.mark.parametrize(
    "axes",
    [
        dict(num_stages=2, tp=2),
        # block decode through the ring-attention KV layout: per-shard cache
        # slices + global RoPE positions inside the lax.scan block path
        dict(num_stages=2, tp=2, sp=2),
    ],
    ids=lambda a: "-".join(f"{k}{v}" for k, v in a.items()),
)
def test_block_decode_greedy_parity(params, axes):
    """Mesh block decode (K steps inside the compiled program) streams the
    same greedy tokens as single-step mesh and all-local generation."""
    settings = SamplerSettings(**GREEDY)
    g = MeshGenerator(CFG, params, settings=settings, block_size=4, **axes)
    g.set_prompt([5, 9, 2, 11])
    got = [g.next_token(i).id for i in range(9)]
    assert got == _local_stream(params, [5, 9, 2, 11], 9, settings)


def test_sampled_stream_invariant_across_paths(params):
    """One seed -> one stochastic stream, regardless of execution path:
    local, local blocked, mesh, mesh blocked all reproduce the same tokens
    (token-index key schedule everywhere; dp fold and batch split are
    identity in the single-stream case)."""
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=11)
    local = _local_stream(params, [5, 9, 2], 9, settings)

    def mesh_stream(**kw):
        g = MeshGenerator(CFG, params, settings=settings, **kw)
        g.set_prompt([5, 9, 2])
        return [g.next_token(i).id for i in range(9)]

    assert mesh_stream(num_stages=2) == local
    assert mesh_stream(num_stages=2, block_size=4) == local
    from cake_tpu.runtime.generator import LlamaGenerator

    g = LlamaGenerator(CFG, params, settings=settings, block_size=4)
    g.set_prompt([5, 9, 2])
    assert [g.next_token(i).id for i in range(9)] == local
