"""MeshGenerator: the Generator surface over the single-program mesh
pipeline must match the all-local generator token-for-token (the same
golden-parity bar the cross-host runtime is held to in test_distributed)."""

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.runtime.mesh_generator import MeshGenerator

CFG = tiny(max_seq_len=64)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(5))


def _local_stream(params, prompt, n, settings):
    g = LlamaGenerator(CFG, params, settings=settings)
    g.set_prompt(prompt)
    return [g.next_token(i).id for i in range(n)]


@pytest.mark.parametrize(
    "axes",
    [
        dict(num_stages=2),
        dict(tp=2),
        dict(num_stages=2, tp=2),
        dict(num_stages=2, tp=2, sp=2),
    ],
    ids=lambda a: "-".join(f"{k}{v}" for k, v in a.items()),
)
def test_greedy_parity_with_local(params, axes):
    settings = SamplerSettings(**GREEDY)
    g = MeshGenerator(CFG, params, settings=settings, **axes)
    g.set_prompt([5, 9, 2, 11])
    got = [g.next_token(i).id for i in range(6)]
    assert got == _local_stream(params, [5, 9, 2, 11], 6, settings)


def test_second_prompt_resets_stream(params):
    settings = SamplerSettings(**GREEDY)
    g = MeshGenerator(CFG, params, settings=settings, num_stages=2, tp=2)
    g.set_prompt([3, 1, 4])
    first = [g.next_token(i).id for i in range(5)]
    g.set_prompt([3, 1, 4])
    assert [g.next_token(i).id for i in range(5)] == first
    # and a different prompt actually changes the stream
    g.set_prompt([9, 8, 7, 6, 5])
    assert [g.next_token(i).id for i in range(5)] != first


def test_dp_plan_rejected(params):
    from cake_tpu.parallel.mesh import MeshPlan

    plan = MeshPlan.build(CFG, num_stages=2, dp=2)
    with pytest.raises(ValueError, match="dp=1"):
        MeshGenerator(CFG, params, plan=plan)


def test_topology_and_mesh_flags_conflict():
    from cake_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["--model", "x", "--stages", "2", "--topology", "t.yml"]
    )
    assert args.stages == 2 and args.topology == "t.yml"
