"""N-gram speculative decoding (runtime/speculative).

The bar: greedy output BIT-IDENTICAL to plain decode on every stream
(speculation may only change how many tokens land per dispatch, never which
tokens), with tokens-per-dispatch > 1 on self-repeating streams."""

import jax
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.runtime.speculative import SpeculativeGenerator, ngram_propose

CFG = tiny(max_seq_len=128)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(2))


# -- proposal machinery -------------------------------------------------------

def test_ngram_propose_copies_after_last_match():
    #                     0  1  2  3  4  5  6  7
    ctx = [7, 1, 2, 3, 9, 1, 2, 3]
    # trailing 3-gram (1,2,3) matched at position 1; continuation is [9, 1, 2]
    assert ngram_propose(ctx, n_max=3, k=3) == [9, 1, 2]


def test_ngram_propose_backs_off_to_shorter_ngrams():
    ctx = [5, 8, 5, 9, 5]  # trailing (9,5) unseen; trailing (5) -> after idx 2
    assert ngram_propose(ctx, n_max=2, k=2) == [9, 5]


def test_ngram_propose_no_match_or_degenerate():
    assert ngram_propose([1, 2, 3], n_max=3, k=4) == []
    assert ngram_propose([4], n_max=3, k=4) == []
    assert ngram_propose([], n_max=3, k=4) == []


def test_ngram_propose_most_recent_match_wins():
    ctx = [1, 2, 7, 1, 2, 8, 1, 2]
    assert ngram_propose(ctx, n_max=2, k=1) == [8]  # the later occurrence


# -- greedy exactness ---------------------------------------------------------

def _plain(params, prompt, n, settings):
    g = LlamaGenerator(CFG, params, settings=settings)
    g.set_prompt(prompt)
    out = []
    for i in range(n):
        t = g.next_token(i)
        out.append(t.id)
        if t.is_end_of_stream:
            break
    return out


def _spec(params, prompt, n, settings, **kw):
    g = SpeculativeGenerator(CFG, params, settings=settings, **kw)
    g.set_prompt(prompt)
    out = []
    for i in range(n):
        t = g.next_token(i)
        out.append(t.id)
        if t.is_end_of_stream:
            break
    return out, g


@pytest.mark.parametrize("prompt", [
    [5, 9, 2, 5, 9, 2, 5, 9],          # self-repeating: high acceptance
    [3, 1, 4, 1, 5, 9, 2, 6],          # mixed
    [11, 7],                           # short, nothing to match at first
])
def test_greedy_tokens_bit_identical_to_plain_decode(params, prompt):
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    want = _plain(params, prompt, 24, settings)
    got, _ = _spec(params, prompt, 24, settings, spec_k=6)
    assert got == want


def test_no_repeat_penalty_path_also_exact(params):
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompt = [2, 8, 2, 8, 2, 8]
    want = _plain(params, prompt, 24, settings)
    got, _ = _spec(params, prompt, 24, settings, spec_k=8)
    assert got == want


def test_speculation_reduces_dispatches_on_repeating_stream(params):
    """A greedy stream that cycles (tiny random models loop readily; the
    prompt seeds the loop) must land >1 token per dispatch on average."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompt = [5, 9, 2, 5, 9, 2, 5, 9]
    got, g = _spec(params, prompt, 32, settings, spec_k=6)
    # accepted tokens either streamed out or are still buffered
    assert g.emitted == len(got) + len(g._block_buf)
    assert g.dispatches < g.emitted  # strictly fewer dispatches than tokens
    assert got == _plain(params, prompt, 32, settings)


def test_eos_inside_speculation_stops_stream(params):
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompt = [5, 9, 2, 5, 9, 2, 5, 9]
    ref = _plain(params, prompt, 12, settings)
    eos_cfg = tiny(max_seq_len=128, eos_token_id=ref[5])
    g = SpeculativeGenerator(eos_cfg, params,
                             settings=settings, spec_k=8)
    g.set_prompt(prompt)
    out = []
    for i in range(12):
        t = g.next_token(i)
        out.append(t.id)
        if t.is_end_of_stream:
            break
    assert out == ref[:6]
    assert out[-1] == ref[5]


def test_window_edge_falls_back_to_single_steps(params):
    """Near max_seq the verification round would overrun the window: the
    generator falls back to plain single steps and still matches."""
    cfg = tiny(max_seq_len=32)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompt = [5, 9, 2, 5, 9, 2] * 3  # 18 tokens, 14 slots left
    plain = LlamaGenerator(cfg, params, settings=settings)
    plain.set_prompt(prompt)
    want = [plain.next_token(i).id for i in range(13)]
    g = SpeculativeGenerator(cfg, params, settings=settings, spec_k=8)
    g.set_prompt(prompt)
    got = [g.next_token(i).id for i in range(13)]
    assert got == want


# -- rejection sampling (temperature > 0) -------------------------------------

def test_rejection_accept_preserves_distribution():
    """Statistical contract of accept_sampled_fn: each emitted token's
    conditional distribution equals the plain sampler's categorical p,
    whether the proposal is likely, unlikely, or a -1 pad. Empirical TV
    distance over many independent round keys vs the exact p."""
    import jax.numpy as jnp

    from cake_tpu.ops import sampling
    from cake_tpu.runtime.speculative import accept_sampled_fn

    v, k, n = 32, 3, 8000
    settings = SamplerSettings(temperature=1.0, top_k=12,
                               repeat_penalty=1.0)
    logits = jax.random.normal(jax.random.PRNGKey(0), (k + 1, v),
                               jnp.float32) * 2.0
    history = jnp.full((settings.repeat_last_n,), -1, jnp.int32)
    hist_slot = jnp.zeros((), jnp.int32)
    eos = jnp.asarray([-1], jnp.int32)
    p0 = np.asarray(jax.nn.softmax(
        sampling.processed_logits(logits[0], history, settings)))
    p1 = np.asarray(jax.nn.softmax(
        sampling.processed_logits(logits[1], history, settings)))

    def run(proposals):
        keys = jax.random.split(jax.random.PRNGKey(7), n)
        toks, count, _, _ = jax.vmap(
            lambda key: accept_sampled_fn(
                logits, proposals, history, hist_slot, eos, key,
                settings=settings)
        )(keys)
        return np.asarray(toks), np.asarray(count)

    for prop0 in (int(np.argmax(p0)),     # likely proposal
                  int(np.argmin(p0)),     # unlikely (often masked: p=0)
                  -1):                    # pad row: no proposal
        props = jnp.asarray([prop0, 5, -1], jnp.int32)
        toks, count = run(props)
        # token 0 marginal == p0 regardless of the proposal
        freq = np.bincount(toks[:, 0], minlength=v) / n
        assert np.abs(freq - p0).sum() < 0.08, (prop0, np.abs(freq - p0).sum())
        assert (count >= 1).all()
        # token 1, conditioned on the round reaching it (row keys are
        # independent, so conditioning on acceptance at row 0 is unbiased)
        sel = toks[count >= 2, 1]
        if sel.size > 500:
            freq1 = np.bincount(sel, minlength=v) / sel.size
            assert np.abs(freq1 - p1).sum() < 0.12


def test_sampled_spec_stream_distribution(params):
    """End-to-end: SpeculativeGenerator with temperature > 0 emits streams
    whose per-position token frequencies match plain decode over many
    seeds (distribution-identical, not sample-path-identical)."""
    settings = SamplerSettings(temperature=1.0, top_k=8, repeat_penalty=1.1)
    prompt = [5, 9, 2, 5, 9, 2, 5, 9]
    trials, steps = 250, 5

    plain = LlamaGenerator(CFG, params, settings=settings)
    spec = SpeculativeGenerator(CFG, params, settings=settings, spec_k=4)

    def streams(gen):
        out = np.zeros((trials, steps), np.int64)
        for t in range(trials):
            gen._key = jax.random.PRNGKey(10_000 + t)
            gen.set_prompt(list(prompt))
            for i in range(steps):
                out[t, i] = gen.next_token(i).id
        return out

    a, b = streams(plain), streams(spec)
    # per-position unigram TV distance (first position is the most
    # constrained; later positions accumulate prefix divergence but remain
    # draws from the same process)
    for i in range(steps):
        va = np.bincount(a[:, i], minlength=CFG.vocab_size) / trials
        vb = np.bincount(b[:, i], minlength=CFG.vocab_size) / trials
        tv = 0.5 * np.abs(va - vb).sum()
        assert tv < 0.22, (i, tv)
    # speculation still lands > 1 token per dispatch on this repeating
    # stream even with sampling in the loop
    assert spec.emitted > spec.dispatches


def test_sampled_spec_accepts_and_matches_greedy_when_peaked(params):
    """Sanity: with temperature > 0 the generator runs, emits in-range
    tokens, and the greedy regression (temperature 0) is untouched."""
    settings = SamplerSettings(temperature=0.7, top_k=4, repeat_penalty=1.1)
    out, g = _spec(params, [5, 9, 2, 5, 9, 2, 5, 9], 10, settings)
    assert len(out) == 10 and all(0 <= t < CFG.vocab_size for t in out)
    assert g.emitted >= g.dispatches


@pytest.mark.parametrize("stages,tp", [(2, 1), (2, 2)])
def test_mesh_speculation_bit_identical_and_fewer_dispatches(params,
                                                             stages, tp):
    """Speculation over the (stage, tp) mesh pipeline: one verification
    program per round across all chips, tokens bit-identical to the plain
    mesh run, tokens-per-dispatch > 1 on a repeating stream."""
    from cake_tpu.runtime.mesh_generator import MeshGenerator
    from cake_tpu.runtime.speculative import MeshSpeculativeGenerator

    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompt = [5, 9, 2, 5, 9, 2, 5, 9]
    ref = MeshGenerator(CFG, params, settings=settings, num_stages=stages,
                        tp=tp)
    ref.set_prompt(prompt)
    want = [ref.next_token(i).id for i in range(24)]
    g = MeshSpeculativeGenerator(CFG, params, settings=settings,
                                 num_stages=stages, tp=tp, spec_k=6)
    g.set_prompt(prompt)
    got = [g.next_token(i).id for i in range(24)]
    assert got == want
    assert g.dispatches < g.emitted


def test_mesh_speculation_composes_with_pipelined_prefill(params):
    """--prefill-chunks (GPipe prompt overlap for TTFT) and speculation
    (decode) touch different phases; together they match the plain run."""
    from cake_tpu.runtime.mesh_generator import MeshGenerator
    from cake_tpu.runtime.speculative import MeshSpeculativeGenerator

    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompt = [5, 9, 2, 5, 9, 2, 5, 9]
    ref = MeshGenerator(CFG, params, settings=settings, num_stages=2)
    ref.set_prompt(prompt)
    want = [ref.next_token(i).id for i in range(16)]
    g = MeshSpeculativeGenerator(CFG, params, settings=settings,
                                 num_stages=2, spec_k=4, prefill_chunks=2)
    g.set_prompt(prompt)
    assert [g.next_token(i).id for i in range(16)] == want


def test_mesh_speculation_with_int8_kv(params):
    from cake_tpu.runtime.speculative import MeshSpeculativeGenerator

    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    prompt = [5, 9, 2, 5, 9, 2]
    g = MeshSpeculativeGenerator(CFG, params, settings=settings,
                                 num_stages=2, kv_quant="int8", spec_k=4)
    g.set_prompt(prompt)
    got = [g.next_token(i).id for i in range(12)]
    # parity with the single-chip int8-KV speculative run (same numerics:
    # both paths quantize-on-write the same values)
    s = SpeculativeGenerator(CFG, params, settings=settings,
                             kv_quant="int8", spec_k=4)
    s.set_prompt(prompt)
    assert got == [s.next_token(i).id for i in range(12)]


def test_int8_kv_composes_with_speculation(params):
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    prompt = [5, 9, 2, 5, 9, 2]
    want, _ = _spec(params, prompt, 16, settings, spec_k=4)
    got, _ = _spec(params, prompt, 16, settings, spec_k=4, kv_quant="int8")
    # int8 KV changes numerics slightly; the contract here is that the two
    # SPECULATIVE runs each match their own plain-decode twins
    g = LlamaGenerator(CFG, params, settings=settings, kv_quant="int8")
    g.set_prompt(prompt)
    plain_int8 = [g.next_token(i).id for i in range(16)]
    assert got == plain_int8[: len(got)]


def test_rejection_accept_preserves_distribution_top_p():
    """Same statistical contract through the top-p (nucleus) transform —
    the masked-out tail must stay at zero probability through acceptance
    AND residual sampling."""
    import jax.numpy as jnp

    from cake_tpu.ops import sampling
    from cake_tpu.runtime.speculative import accept_sampled_fn

    v, k, n = 24, 2, 6000
    settings = SamplerSettings(temperature=0.8, top_p=0.7,
                               repeat_penalty=1.0)
    logits = jax.random.normal(jax.random.PRNGKey(3), (k + 1, v),
                               jnp.float32) * 2.0
    history = jnp.full((settings.repeat_last_n,), -1, jnp.int32)
    eos = jnp.asarray([-1], jnp.int32)
    p0 = np.asarray(jax.nn.softmax(
        sampling.processed_logits(logits[0], history, settings)))
    prop = int(np.argsort(p0)[-2])  # second-most-likely: real accept/reject mix
    props = jnp.asarray([prop, -1], jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(9), n)
    toks, count, _, _ = jax.vmap(
        lambda key: accept_sampled_fn(
            logits, props, history, jnp.zeros((), jnp.int32), eos, key,
            settings=settings)
    )(keys)
    toks, count = np.asarray(toks), np.asarray(count)
    freq = np.bincount(toks[:, 0], minlength=v) / n
    assert np.abs(freq - p0).sum() < 0.08
    # nucleus-masked tokens never appear
    assert freq[p0 == 0].sum() == 0.0


# -- fused multi-round speculation (device-side propose) ----------------------

def test_device_propose_matches_host():
    """ngram_propose_device must reproduce the host proposer's -1-padded
    array bit-for-bit (longest-n-first, most-recent hit, end clamp)."""
    import jax.numpy as jnp

    from cake_tpu.runtime.speculative import ngram_propose_device

    rng = np.random.default_rng(7)
    for _ in range(40):
        L = int(rng.integers(2, 40))
        ctx_list = rng.integers(0, 6, size=L).tolist()  # small vocab: hits
        k, n_max = 5, 3
        want = np.full((k,), -1, np.int64)
        prop = ngram_propose(ctx_list, n_max, k)
        want[: len(prop)] = prop
        buf = np.zeros((64,), np.int32)
        buf[:L] = ctx_list
        got = np.asarray(
            ngram_propose_device(jnp.asarray(buf), jnp.int32(L),
                                 n_max=n_max, k=k)
        )
        assert got.tolist() == want.tolist(), (ctx_list, got, want)


def test_fused_matches_host_loop_and_syncs_less(params):
    """spec_rounds=8 (fused) must emit the same greedy stream as
    spec_rounds=1 (per-round host loop) with ~rounds/dispatch fewer
    dispatches."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    prompt = [5, 9, 2, 5, 9, 2, 5, 9]
    want, host = _spec(params, prompt, 40, settings, spec_k=6,
                       spec_rounds=1)
    got, fused = _spec(params, prompt, 40, settings, spec_k=6,
                       spec_rounds=8)
    assert got == want
    assert fused._spec_block is not None and host._spec_block is None
    # one device sync per 8 rounds: far fewer dispatches for the same
    # emission count
    assert fused.dispatches < host.dispatches
    assert fused.emitted >= host.emitted  # fused block may overshoot n


def test_fused_sampled_stream_invariant_to_rounds_per_dispatch(params):
    """temperature>0: the fused key schedule depends only on the stream
    position (fold_in(fold_in(key, 0x5bec), pos)), never on how rounds are
    grouped into dispatches — so any spec_rounds>1 settings yield the SAME
    sampled stream bit-for-bit. (Host-loop parity can't be bitwise in
    sampled mode: its no-proposal rounds fall back to the single-step
    program whose keys live in the fold_in(key, index) domain;
    test_sampled_spec_stream_distribution covers that equivalence at the
    distribution level.)"""
    settings = SamplerSettings(temperature=0.7, repeat_penalty=1.0,
                               seed=11)
    prompt = [5, 9, 2, 5, 9, 2, 5, 9, 2, 5, 9, 2]
    want, _ = _spec(params, prompt, 24, settings, spec_k=4, spec_rounds=2)
    got, _ = _spec(params, prompt, 24, settings, spec_k=4, spec_rounds=8)
    assert got == want


def test_fused_eos_freezes_trailing_rounds(params):
    """EOS inside a fused block: rounds after the EOS round emit nothing
    and the stream's tokens match the host loop's exactly."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    ref = _plain(params, [5, 9, 2, 5, 9, 2, 5, 9], 24, settings)
    eos_cfg = tiny(max_seq_len=128, eos_token_id=ref[5])
    g = SpeculativeGenerator(eos_cfg, params, settings=settings, spec_k=6,
                             spec_rounds=8)
    g.set_prompt([5, 9, 2, 5, 9, 2, 5, 9])
    out = []
    for i in range(24):
        t = g.next_token(i)
        out.append(t.id)
        if t.is_end_of_stream:
            break
    assert out == ref[:6]


def test_fused_device_ctx_tracks_true_context(params):
    """After fused dispatches the device ctx buffer must hold EXACTLY
    prompt + every device-emitted token (ctx[pos] = last): a shifted or
    clobbered buffer silently degrades proposals (r4 review repro)."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    g = SpeculativeGenerator(CFG, params, settings=settings, spec_k=6,
                             spec_rounds=4)
    g.set_prompt([5, 9, 2, 5, 9, 2, 5, 9])
    for i in range(20):
        g.next_token(i)
    assert g._ctx is not None and g._ctx_synced_pos == g._pos
    true_ctx = g._prompt_tokens + g._generated + list(g._block_buf)
    got = np.asarray(g._ctx)[: g._pos + 1].tolist()
    assert got == true_ctx


def test_fused_ctx_invalidated_on_new_prompt(params):
    """set_prompt must drop the device ctx: a second stream whose prefill
    position collides with the first stream's synced position must not
    propose from the first stream's tokens."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    g = SpeculativeGenerator(CFG, params, settings=settings, spec_k=6,
                             spec_rounds=4)
    g.set_prompt([5, 9, 2, 5, 9, 2, 5, 9])
    for i in range(12):
        g.next_token(i)
    assert g._ctx is not None
    g.set_prompt([7, 1, 3, 7, 1, 3, 7, 1])
    assert g._ctx is None and g._ctx_synced_pos == -1
    out = [g.next_token(i).id for i in range(12)]
    assert out == _plain(params, [7, 1, 3, 7, 1, 3, 7, 1], 12, settings)


def test_spec_replay_teacher_forced_counts_match_host_reference(params):
    """r5: the fused corpus replay (bench CAKE_BENCH_SPEC_CORPUS) must
    accept exactly the run lengths a host-side teacher-forced simulation
    of the same n-gram proposer produces on the same stream — the device
    proposer, the forced accept, and the position bookkeeping all agree;
    and the logits checksum is finite (the verify forward was not DCE'd)."""
    import jax.numpy as jnp
    from functools import partial

    from cake_tpu.ops.kvcache import init_cache
    from cake_tpu.runtime.generator import prefill_fn
    from cake_tpu.runtime.speculative import spec_replay_fn
    from cake_tpu.utils.corpus import corpus_tokens

    k, n_max, rounds, prompt_len = 4, 3, 6, 16
    toks = corpus_tokens(CFG.vocab_size)[: CFG.max_seq_len]

    cache = init_cache(CFG, batch=1, max_seq=CFG.max_seq_len)
    prefill = jax.jit(partial(prefill_fn, config=CFG),
                      donate_argnames=("cache",))
    _, cache = prefill(params, jnp.asarray(toks[None, :prompt_len]), cache,
                       jnp.asarray([prompt_len - 1], jnp.int32))
    replay = jax.jit(
        partial(spec_replay_fn, config=CFG, k=k, n_max=n_max, rounds=rounds),
        donate_argnames=("cache",),
    )
    counts, pos, cache, acc = replay(
        params, jnp.asarray(toks), jnp.int32(prompt_len), cache,
        jnp.float32(0.0),
    )
    counts = np.asarray(counts)

    # host reference: same propose convention (slots 0..p valid), forced
    # accept = leading proposal/corpus matches + 1
    p = prompt_len
    want = []
    for _ in range(rounds):
        props = ngram_propose(toks[: p + 1].tolist(), n_max, k)
        props = props + [-1] * (k - len(props))
        c = 1
        for i in range(k):
            if props[i] == int(toks[p + 1 + i]):
                c += 1
            else:
                break
        want.append(c)
        p += c

    assert counts.tolist() == want
    assert int(pos) == p
    assert 1 <= counts.min() and counts.max() <= k + 1
    assert np.isfinite(float(acc))
