"""Master/worker loopback: distributed generation must match local exactly.

The reference was only ever validated by manual multi-node deployment
(SURVEY.md §4); here the whole master<->worker path — wire framing, tensor
codec, worker op loop, per-connection caches, segment coalescing — runs over
localhost and is held to golden-token parity with the all-local generator.
"""

import threading

import jax
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime.master import DistributedGenerator, build_runners
from cake_tpu.runtime.worker import Worker
from cake_tpu.runtime.generator import LlamaGenerator

CFG = tiny(max_seq_len=64)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(3))


def _loader(params):
    return lambda lo, hi: jax.tree.map(lambda a: a[lo:hi], params["layers"])


def _head_params(params):
    return {k: params[k] for k in ("embed", "norm_f", "lm_head")}


def _start_worker(name, topo, params, port=0):
    w = Worker(
        name, CFG, topo, _loader(params), address=f"127.0.0.1:{port}",
        max_seq=CFG.max_seq_len,
    )
    w.serve_in_background()
    return w


def _local_stream(params, prompt, n, settings):
    g = LlamaGenerator(CFG, params, settings=settings)
    g.set_prompt(prompt)
    return [g.next_token(i).id for i in range(n)]


def test_all_remote_two_workers(params):
    """Master holds no layers; two workers serve [0,2) and [2,4)."""
    w1 = _start_worker("w1", Topology.from_dict(
        {"w1": {"layers": ["model.layers.0-1"]}}), params)
    w2 = _start_worker("w2", Topology.from_dict(
        {"w2": {"layers": ["model.layers.2-3"]}}), params)
    topo = Topology.from_dict({
        "w1": {"host": f"127.0.0.1:{w1.port}", "layers": ["model.layers.0-1"]},
        "w2": {"host": f"127.0.0.1:{w2.port}", "layers": ["model.layers.2-3"]},
    })
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    runners = build_runners(CFG, topo, _loader(params))
    assert [r.ident() for r in runners] == [
        f"127.0.0.1:{w1.port}", f"127.0.0.1:{w2.port}"
    ]
    g = DistributedGenerator(CFG, _head_params(params), runners,
                             settings=settings)
    g.set_prompt([5, 9, 2])
    got = [g.next_token(i).id for i in range(6)]
    assert got == _local_stream(params, [5, 9, 2], 6, settings)
    assert g.tokens_per_sec() is not None
    stats = g.runner_stats()
    assert [s["layers"] for s in stats] == ["0-1", "2-3"]
    # 6 forwards per runner; the first (prefill + compile) is warm-up
    assert all(s["calls"] == 5 and s["avg_ms"] > 0 for s in stats)
    assert all(s["warmup_ms"] > 0 for s in stats)
    assert all("handshake_ms" in s for s in stats)
    g.close()
    w1.shutdown()
    w2.shutdown()


def test_mixed_local_remote(params):
    """Worker serves the middle segment; master runs layers 0 and 3 locally
    (llama.rs:177-193 semantics: per-layer placement by topology)."""
    w = _start_worker("mid", Topology.from_dict(
        {"mid": {"layers": ["model.layers.1-2"]}}), params)
    topo = Topology.from_dict({
        "mid": {"host": f"127.0.0.1:{w.port}", "layers": ["model.layers.1-2"]},
    })
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    runners = build_runners(CFG, topo, _loader(params))
    idents = [r.ident() for r in runners]
    assert idents == ["local", f"127.0.0.1:{w.port}", "local"]
    g = DistributedGenerator(CFG, _head_params(params), runners,
                             settings=settings)
    g.set_prompt([1, 2, 3, 4])
    got = [g.next_token(i).id for i in range(5)]
    assert got == _local_stream(params, [1, 2, 3, 4], 5, settings)
    g.close()
    w.shutdown()


def test_sampled_stream_parity(params):
    """Seeded non-greedy sampling also matches local exactly (same sampler,
    same key schedule)."""
    w = _start_worker("all", Topology.from_dict(
        {"all": {"layers": ["model.layers.0-3"]}}), params)
    topo = Topology.from_dict({
        "all": {"host": f"127.0.0.1:{w.port}", "layers": ["model.layers.0-3"]},
    })
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=77)
    runners = build_runners(CFG, topo, _loader(params))
    g = DistributedGenerator(CFG, _head_params(params), runners,
                             settings=settings)
    g.set_prompt([3, 1, 4])
    got = [g.next_token(i).id for i in range(8)]
    assert got == _local_stream(params, [3, 1, 4], 8, settings)
    g.close()
    w.shutdown()


def test_generator_reuse_reconnects(params):
    """set_prompt on a distributed generator resets worker-side caches via
    reconnect (reference: fresh connection = fresh cache, worker.rs:52-61)."""
    w = _start_worker("all", Topology.from_dict(
        {"all": {"layers": ["model.layers.0-3"]}}), params)
    topo = Topology.from_dict({
        "all": {"host": f"127.0.0.1:{w.port}", "layers": ["model.layers.0-3"]},
    })
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    runners = build_runners(CFG, topo, _loader(params))
    g = DistributedGenerator(CFG, _head_params(params), runners,
                             settings=settings)
    g.set_prompt([9, 8, 7])
    first = [g.next_token(i).id for i in range(4)]
    g.set_prompt([9, 8, 7])
    second = [g.next_token(i).id for i in range(4)]
    assert first == second
    g.close()
    w.shutdown()


def test_worker_int8_kv_serves_deterministically(params):
    """A worker can hold its per-connection KV caches in int8 (half the
    cache HBM on that host); generation is deterministic and per-connection
    isolation still holds (reconnect -> identical stream)."""
    w = Worker(
        "all", CFG, Topology.from_dict({"all": {"layers": ["model.layers.0-3"]}}),
        _loader(params), address="127.0.0.1:0", max_seq=CFG.max_seq_len,
        kv_quant="int8",
    )
    w.serve_in_background()
    topo = Topology.from_dict({
        "all": {"host": f"127.0.0.1:{w.port}", "layers": ["model.layers.0-3"]},
    })
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    runners = build_runners(CFG, topo, _loader(params))
    g = DistributedGenerator(CFG, _head_params(params), runners,
                             settings=settings)
    g.set_prompt([5, 9, 2])
    first = [g.next_token(i).id for i in range(6)]
    g.set_prompt([5, 9, 2])  # reconnect -> fresh int8 caches
    second = [g.next_token(i).id for i in range(6)]
    assert first == second and len(first) == 6
    g.close()
    w.shutdown()


def test_handshake_warns_on_version_skew(params, monkeypatch, caplog):
    """A skewed master/worker pair must not handshake silently
    (proto/message.rs:37-53 carries version for exactly this)."""
    import logging

    import cake_tpu
    from cake_tpu.parallel.runner import RemoteRunner

    w = _start_worker("w", Topology.from_dict(
        {"w": {"layers": ["model.layers.0-3"]}}), params)
    monkeypatch.setattr(cake_tpu, "__version__", "999.0.0")
    with caplog.at_level(logging.WARNING, logger="cake_tpu.runner"):
        r = RemoteRunner(f"127.0.0.1:{w.port}", start=0, stop=4)
    assert any("version skew" in rec.message for rec in caplog.records)
    assert r.info.device_idx >= 0
    r.close()
    w.shutdown()


def test_worker_rejects_unserved_layer(params):
    from cake_tpu.parallel.runner import RemoteRunner

    w = _start_worker("w", Topology.from_dict(
        {"w": {"layers": ["model.layers.0-1"]}}), params)
    with pytest.raises(RuntimeError, match="does not serve"):
        RemoteRunner(f"127.0.0.1:{w.port}", start=2, stop=4)
    w.shutdown()


def test_worker_reports_op_errors(params):
    """A malformed op gets an Error reply, and the connection keeps serving."""
    from cake_tpu.runtime import protocol, wire
    from cake_tpu.runtime.protocol import MsgType

    w = _start_worker("w", Topology.from_dict(
        {"w": {"layers": ["model.layers.0-1"]}}), params)
    conn = wire.connect("127.0.0.1", w.port)
    conn.send(MsgType.HELLO)
    t, payload = conn.recv()
    assert t == MsgType.WORKER_INFO
    x = np.zeros((1, 1, CFG.hidden_size), np.float32)
    conn.send(MsgType.BATCH, protocol.encode_ops(x, [("model.layers.3", 0)]))
    t, payload = conn.recv()
    assert t == MsgType.ERROR
    assert "not served" in protocol.decode_error(payload)
    # connection still alive: valid op succeeds
    conn.send(MsgType.BATCH, protocol.encode_ops(x, [("model.layers.0", 0)]))
    t, payload = conn.recv()
    assert t == MsgType.TENSOR
    conn.close()
    w.shutdown()


def test_worker_requires_assigned_layers(params):
    with pytest.raises(ValueError, match="not present"):
        Worker("ghost", CFG, Topology.from_dict({}), _loader(params))


def test_mid_stream_worker_restart_recovers(params):
    """A worker dying mid-stream does NOT end the generation (unlike the
    reference, client.rs:52-61): the master reconnects and replays the
    context, and the greedy stream is identical to an uninterrupted run."""
    node_topo = Topology.from_dict({"w": {"layers": ["model.layers.1-2"]}})
    w = _start_worker("w", node_topo, params)
    port = w.port
    topo = Topology.from_dict({
        "w": {"host": f"127.0.0.1:{port}", "layers": ["model.layers.1-2"]},
    })
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    g = DistributedGenerator(CFG, _head_params(params),
                             build_runners(CFG, topo, _loader(params)),
                             settings=settings)
    g.set_prompt([5, 9, 2])
    got = [g.next_token(i).id for i in range(3)]
    # kill the worker between tokens, then bring a fresh one up on the port
    w.shutdown()
    w2 = _start_worker("w", node_topo, params, port=port)
    got += [g.next_token(i).id for i in range(3, 7)]
    assert got == _local_stream(params, [5, 9, 2], 7, settings)
    assert g.recoveries >= 1  # the replay path actually ran
    g.close()
    w2.shutdown()


def test_worker_op_error_not_retried(params):
    """A worker-reported op error is deterministic: it must surface
    immediately, NOT trigger reconnect + full-context replay (which would
    re-run the same failing op at prefill cost every token)."""
    from cake_tpu.runtime import protocol

    settings = SamplerSettings(temperature=0.0)
    g = DistributedGenerator(CFG, _head_params(params),
                             build_runners(CFG, Topology.from_dict({}),
                                           _loader(params)),
                             settings=settings)
    g.set_prompt([5, 9, 2])
    g.next_token(0)

    def boom(x, pos):
        raise protocol.WorkerOpError("worker 127.0.0.1:1: bad op")

    # forward_jax is the seam the master's segment walk calls
    g.runners[0].forward_jax = boom
    with pytest.raises(protocol.WorkerOpError):
        g.next_token(1)
    assert g.recoveries == 0
    g.close()


def test_recovery_attempts_capped(params):
    """A permanently failing transport gives up after MAX_CONSEC_RECOVERIES
    instead of replaying the context forever."""
    from cake_tpu.runtime import wire

    settings = SamplerSettings(temperature=0.0)
    g = DistributedGenerator(CFG, _head_params(params),
                             build_runners(CFG, Topology.from_dict({}),
                                           _loader(params)),
                             settings=settings)
    g.set_prompt([5, 9, 2])
    g.next_token(0)

    calls = {"n": 0}
    real_forward = g.runners[0].forward_jax

    def flaky(x, pos):
        calls["n"] += 1
        # single-token decode forwards fail; replay prefills (T>1) succeed
        if np.asarray(x).shape[1] == 1:
            raise wire.WireError("connection reset")
        return real_forward(x, pos)

    g.runners[0].forward_jax = flaky
    # each failing decode step replays successfully and yields a token, but
    # the consecutive-recovery counter never resets; the cap must trip
    with pytest.raises(RuntimeError, match="consecutive recovery"):
        for i in range(1, 10):
            g.next_token(i)
    assert g.recoveries == DistributedGenerator.MAX_CONSEC_RECOVERIES
    g.close()


def test_worker_down_for_good_still_fails(params):
    """If the worker never comes back, recovery raises (reference behavior:
    the run errors out, cake-cli/main.rs:51-55)."""
    node_topo = Topology.from_dict({"w": {"layers": ["model.layers.0-3"]}})
    w = _start_worker("w", node_topo, params)
    topo = Topology.from_dict({
        "w": {"host": f"127.0.0.1:{w.port}", "layers": ["model.layers.0-3"]},
    })
    settings = SamplerSettings(temperature=0.0)
    # short recovery budget: this test asserts the permanent-failure path,
    # not the (default 30s/replica) reconnect patience
    g = DistributedGenerator(CFG, _head_params(params),
                             build_runners(CFG, topo, _loader(params),
                                           recover_deadline_s=0.3),
                             settings=settings)
    g.set_prompt([1, 2, 3])
    g.next_token(0)
    w.shutdown()
    # the in-flight connection may serve one final op before the worker's
    # loop notices the stop flag; within a few steps the failure must
    # surface (reconnect hits the closed listener)
    with pytest.raises(Exception):
        for i in range(1, 5):
            g.next_token(i)
    g.close()
