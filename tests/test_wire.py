"""Wire transport + protocol round-trips, native C++ <-> Python interop."""

import threading

import numpy as np
import pytest

from cake_tpu.runtime import protocol, wire
from cake_tpu.runtime.protocol import MsgType, WorkerInfo


def test_native_lib_builds():
    assert wire.native_lib() is not None, "g++ build of cake_wire.cc failed"


def _echo_server(listener, n_msgs=1):
    def run():
        conn = listener.accept()
        for _ in range(n_msgs):
            t, payload = conn.recv()
            conn.send(t, payload)
        conn.close()

    th = threading.Thread(target=run, daemon=True)
    th.start()
    return th


@pytest.mark.parametrize("client_py,server_py", [
    (False, False), (True, True), (False, True), (True, False),
])
def test_roundtrip_interop(client_py, server_py):
    """All four combinations of native/python endpoints must interoperate
    (same frame format + CRC)."""
    listener = wire.Listener("127.0.0.1", 0, force_python=server_py)
    th = _echo_server(listener)
    conn = wire.connect("127.0.0.1", listener.port, force_python=client_py)
    payload = b"hello cake" * 100
    conn.send(MsgType.HELLO, payload)
    t, got = conn.recv()
    assert t == MsgType.HELLO
    assert got == payload
    conn.close()
    th.join(timeout=5)
    listener.close()


def test_empty_payload():
    listener = wire.Listener("127.0.0.1", 0)
    th = _echo_server(listener)
    conn = wire.connect("127.0.0.1", listener.port)
    conn.send(MsgType.GOODBYE)
    t, got = conn.recv()
    assert t == MsgType.GOODBYE and got == b""
    conn.close()
    th.join(timeout=5)
    listener.close()


def test_peer_close_raises():
    listener = wire.Listener("127.0.0.1", 0)

    def run():
        conn = listener.accept()
        conn.close()

    th = threading.Thread(target=run, daemon=True)
    th.start()
    conn = wire.connect("127.0.0.1", listener.port)
    th.join(timeout=5)
    with pytest.raises(wire.PeerClosed):
        conn.recv()
    conn.close()
    listener.close()


def test_oversized_payload_rejected():
    conn = wire.Connection(sock=None)
    with pytest.raises(wire.WireError):
        conn.send(MsgType.TENSOR, b"x" * (wire.MAX_PAYLOAD + 1))


# -- protocol codecs --------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "float16", "int32", "int8"])
def test_tensor_codec_roundtrip(dtype):
    if dtype == "bfloat16":
        import ml_dtypes

        arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4).astype(
            ml_dtypes.bfloat16
        )
    else:
        arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4).astype(dtype)
    out = protocol.decode_tensor(protocol.encode_tensor(arr))
    assert out.shape == arr.shape
    assert out.dtype == arr.dtype
    np.testing.assert_array_equal(out, arr)


def test_tensor_codec_scalar_and_empty():
    s = np.float32(3.5)
    out = protocol.decode_tensor(protocol.encode_tensor(s))
    assert out.shape == () and float(out) == 3.5


def test_tensor_codec_rejects_truncated():
    buf = protocol.encode_tensor(np.ones((4, 4), np.float32))
    with pytest.raises(ValueError):
        protocol.decode_tensor(buf[:-3])


def test_worker_info_roundtrip():
    wi = WorkerInfo(name="w0", device="TPU v5e", device_idx=3,
                    dtype="bfloat16",
                    layers=["model.layers.0", "model.layers.1"])
    got = WorkerInfo.from_bytes(wi.to_bytes())
    assert got.name == "w0"
    assert got.layers == wi.layers
    assert got.device_idx == 3
    assert "w0" in str(got)


def test_worker_info_carries_identity_fields():
    """Reference parity (proto/message.rs:37-53): version/os/arch/device
    ordinal travel in the handshake so a skewed pair is detectable."""
    import platform

    from cake_tpu import __version__

    got = WorkerInfo.from_bytes(WorkerInfo(name="w0").to_bytes())
    assert got.version == __version__
    assert got.os == platform.system()
    assert got.arch == platform.machine()
    assert got.device_idx == 0


def test_ops_codec_roundtrip():
    x = np.random.RandomState(0).randn(1, 3, 8).astype(np.float32)
    ops = [("model.layers.4", 7), ("model.layers.5", 7)]
    x2, ops2, codec = protocol.decode_ops(protocol.encode_ops(x, ops))
    np.testing.assert_array_equal(x, x2)
    assert ops2 == ops
    assert codec == "none"


def test_multipart_payload_send(monkeypatch):
    """A buffer-sequence payload (the zero-copy activation path) frames
    identically to the equivalent contiguous bytes, across native and
    Python endpoints."""
    arr = np.arange(512, dtype=np.float32).reshape(4, 128)
    parts = protocol.encode_ops_parts(arr, [("model.layers.0", 3)])
    flat = protocol.encode_ops(arr, [("model.layers.0", 3)])
    assert b"".join(bytes(p) for p in parts) == flat
    for server_py in (False, True):
        listener = wire.Listener("127.0.0.1", 0, force_python=server_py)
        th = _echo_server(listener)
        conn = wire.connect("127.0.0.1", listener.port,
                            force_python=not server_py)
        conn.send(MsgType.BATCH, parts)
        t, got = conn.recv()
        assert t == MsgType.BATCH and got == flat
        conn.close()
        th.join(timeout=5)
        listener.close()
