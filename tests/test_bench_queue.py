"""Bench drives run UNATTENDED in the first healthy chip window — a
typo'd CAKE_BENCH_* knob would silently measure the wrong row with
nobody watching. The drives live in the Makefile bench targets (the old
`tools_bench_queue*.sh` scratch queues are gone): pin every env var
those targets set to the set bench.py actually reads, and every tool
they invoke to a real module."""

import re
from pathlib import Path

_ROOT = Path(__file__).resolve().parent.parent


def _bench_known_vars() -> set:
    src = (_ROOT / "bench.py").read_text()
    return set(re.findall(r'environ(?:\.get)?\[?\(?"(CAKE_BENCH_[A-Z0-9_]+)"',
                          src))


def test_makefile_env_vars_are_recognized_by_bench():
    known = _bench_known_vars()
    assert "CAKE_BENCH_PRESET" in known  # the extractor itself works
    makefile = (_ROOT / "Makefile").read_text()
    used = re.findall(r"(CAKE_BENCH_[A-Z0-9_]+)=", makefile)
    assert used, "Makefile no longer drives bench.py?"
    for var in used:
        assert var in known, (
            f"Makefile sets {var}, which bench.py never reads — the row "
            "would silently measure the wrong thing"
        )


def test_makefile_tools_exist():
    makefile = (_ROOT / "Makefile").read_text()
    names = re.findall(r"cake_tpu\.tools\.([a-z0-9_]+)", makefile)
    assert names, "Makefile no longer invokes any cake_tpu.tools module?"
    for name in names:
        assert (_ROOT / "cake_tpu" / "tools" / f"{name}.py").exists(), (
            f"Makefile invokes cake_tpu.tools.{name}, which does not exist"
        )


def test_no_scratch_queue_scripts_return():
    """The wait-then-measure scratch scripts were folded into bench.py +
    Makefile targets; a returning tools_bench_queue*.sh would dodge the
    env-var pinning above."""
    assert sorted(_ROOT.glob("tools_bench_queue*.sh")) == []
