"""The wait-then-measure queues run UNATTENDED in the first healthy chip
window — a typo'd CAKE_BENCH_* knob would silently measure the wrong row
with nobody watching. Pin every env var the queue scripts set to the set
bench.py actually reads, and every tool they invoke to a real module."""

import os
import re
from pathlib import Path

_ROOT = Path(__file__).resolve().parent.parent


def _bench_known_vars() -> set:
    src = (_ROOT / "bench.py").read_text()
    return set(re.findall(r'environ(?:\.get)?\[?\(?"(CAKE_BENCH_[A-Z0-9_]+)"',
                          src))


def _queue_scripts():
    return sorted(_ROOT.glob("tools_bench_queue*.sh"))


def test_queue_env_vars_are_recognized_by_bench():
    known = _bench_known_vars()
    assert "CAKE_BENCH_PRESET" in known  # the extractor itself works
    for script in _queue_scripts():
        for var in re.findall(r"(CAKE_BENCH_[A-Z0-9_]+)=",
                              script.read_text()):
            assert var in known, (
                f"{script.name} sets {var}, which bench.py never reads — "
                "the row would silently measure the wrong thing"
            )


def test_queue_tools_exist():
    for script in _queue_scripts():
        for name in re.findall(r"cake_tpu\.tools\.([a-z0-9_]+)",
                               script.read_text()):
            assert (_ROOT / "cake_tpu" / "tools" / f"{name}.py").exists(), (
                f"{script.name} invokes cake_tpu.tools.{name}, which does "
                "not exist"
            )
        # queue5-style indirection: `run_tool NAME ...` resolves to
        # cake_tpu.tools.NAME at runtime — pin those names too
        for name in re.findall(r"run_tool ([a-z0-9_]+)",
                               script.read_text()):
            assert (_ROOT / "cake_tpu" / "tools" / f"{name}.py").exists(), (
                f"{script.name} run_tool {name}: cake_tpu/tools/{name}.py "
                "does not exist"
            )


def test_queue5_runs_the_record_row_first():
    """Safest-first ordering: the metric of record must be the first row
    after a healthy probe (a later row's crashed compile can re-wedge the
    grant — r3/r4 history)."""
    script = (_ROOT / "tools_bench_queue5.sh").read_text()
    rows = re.findall(r"run_row ([^\n]+)", script)
    assert rows and "CAKE_BENCH_PRESET=8b" in rows[0]
    tools = re.findall(r"run_tool ([a-z0-9_]+)", script)
    # the kernel sweeps (which crashed the r4w2 grant) run last
    assert tools[-3:] == ["int4_sweep", "kernel_check", "flash_sweep"]
