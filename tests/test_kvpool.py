"""Paged KV-cache pool (cake_tpu/kvpool): the page pool must be an
invisible layout change.

The contract under test: ``BatchGenerator(kv_layout="paged")`` produces
BIT-IDENTICAL token streams to the slot layout across every serving
scenario — steady batch, mid-run admission, retire-and-reuse,
shared-prefix fan-out, constrained (ISSUE 8) streams — while admission
and retirement touch only host-side page tables (no retrace: the page
map and scatter ids are data operands), n same-prefix streams share
physical prefill pages (``kvpool.pages_shared`` > 0 with engine
``prefix_hits`` >= n-1), and the pool self-manages under pressure
(prefix-tree eviction, admission deferral).
"""

import json
import urllib.request

import jax
import pytest

from cake_tpu.kvpool import PagePool, PoolExhausted, PrefixLRU, PrefixTree
from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator

CFG = tiny(max_seq_len=64)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)
PROMPTS = [[5, 9, 2, 11], [3, 1, 4, 1, 5, 9], [7, 7, 2]]
# a 36-token system prompt: >= prefix_share_min (32) and > 2 full
# 16-token pages, so both sharing paths (set_prompts + admission) engage
PREFIX = list(range(3, 39))


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(5))


def _drive(gen, want_tokens=6, max_steps=300):
    """step() until every live/queued stream has ``want_tokens`` (or is
    done) and no admission is pending — generate() can't drive a batch
    whose live set starts empty."""
    for _ in range(max_steps):
        gen.step()
        if gen.pending_admissions():
            continue
        if all((not s.active) or s.done or len(s.generated) >= want_tokens
               for s in gen.streams):
            break
    return {s.stream_id: list(s.generated)[:want_tokens]
            for s in gen.streams if s.active}


# -- host-side units ---------------------------------------------------------
class TestPagePool:
    def test_alloc_free_refcounts(self):
        p = PagePool(8, 4)
        a = p.alloc()
        assert p.refcount(a) == 1 and p.free_count == 6  # sink excluded
        p.ref(a)
        assert p.shared_count == 1
        assert not p.unref(a)          # still stream-held
        assert p.shared_count == 0
        assert p.unref(a)              # back on the free list
        assert p.free_count == 7

    def test_sink_is_pinned_and_exhaustion_raises(self):
        p = PagePool(4, 4)
        got = {p.alloc() for _ in range(3)}
        assert 0 not in got            # the sink page is never allocated
        with pytest.raises(PoolExhausted):
            p.alloc()

    def test_pow2_and_size_validation(self):
        with pytest.raises(ValueError):
            PagePool(12, 4)            # not a power of two
        with pytest.raises(ValueError):
            PagePool(8, 0)

    def test_unref_free_page_raises(self):
        p = PagePool(8, 4)
        with pytest.raises(ValueError):
            p.unref(3)


class TestPrefixTree:
    def _tree(self, pages=16, ps=4):
        pool = PagePool(pages, ps)
        return pool, PrefixTree(pool)

    def test_insert_match_page_aligned(self):
        pool, t = self._tree()
        ids = list(range(1, 11))       # 10 tokens, ps=4 -> 2 full pages
        pages = [pool.alloc(), pool.alloc()]
        t.insert(ids, pages)
        assert pool.refcount(pages[0]) == 2  # alloc claim + tree claim
        base, got = t.match(ids)
        assert (base, got) == (8, pages)

    def test_match_strictly_shorter_than_prompt(self):
        pool, t = self._tree()
        ids = [1, 2, 3, 4, 5, 6, 7, 8]  # exactly 2 full pages
        t.insert(ids, [pool.alloc(), pool.alloc()])
        # a full-coverage match would leave no remainder token to prefill
        base, got = t.match(ids)
        assert base == 4 and len(got) == 1

    def test_divergent_prefixes_fork(self):
        pool, t = self._tree()
        a, b = pool.alloc(), pool.alloc()
        t.insert([1, 2, 3, 4, 9], [a])
        t.insert([1, 2, 3, 5, 9], [b])
        assert t.match([1, 2, 3, 4, 8, 8])[1] == [a]
        assert t.match([1, 2, 3, 5, 8, 8])[1] == [b]

    def test_eviction_is_lru_and_frees_pages(self):
        pool, t = self._tree()
        a, b = pool.alloc(), pool.alloc()
        t.insert([1, 2, 3, 4, 9], [a])
        t.insert([5, 6, 7, 8, 9], [b])
        t.match([1, 2, 3, 4, 9, 9])    # bump chain a: b is now LRU
        free0 = pool.free_count
        assert t.evict_one()
        assert pool.free_count == free0  # b still holds its alloc claim
        pool.unref(b)                    # stream-side claim drops -> free
        assert pool.free_count == free0 + 1
        assert t.match([5, 6, 7, 8, 9, 9]) == (0, [])
        assert t.match([1, 2, 3, 4, 9, 9])[1] == [a]

    def test_evict_until_free(self):
        pool, t = self._tree(pages=8)
        chains = []
        for k in range(3):
            pid = pool.alloc()
            t.insert([10 * k + 1, 10 * k + 2, 10 * k + 3, 10 * k + 4, 0],
                     [pid])
            pool.unref(pid)            # tree is the only claim
            chains.append(pid)
        assert pool.free_count == 4
        assert t.evict_until_free(6)
        assert pool.free_count >= 6


class TestPrefixLRU:
    """Regression for the legacy slot store's LRU semantics (the old
    dict pop-reinsert / next(iter(...)) idiom, now an explicit type)."""

    def test_evicts_least_recently_used_past_cap(self):
        lru = PrefixLRU(2)
        lru.put((1, 2), "a")
        lru.put((3, 4), "b")
        lru.match([1, 2, 9])           # bump (1,2): (3,4) is now LRU
        lru.put((5, 6), "c")
        assert (3, 4) not in lru
        assert (1, 2) in lru and (5, 6) in lru

    def test_put_refreshes_existing_key(self):
        lru = PrefixLRU(2)
        lru.put((1,), "a")
        lru.put((2,), "b")
        lru.put((1,), "a2")            # refresh: (2,) becomes LRU
        lru.put((3,), "c")
        assert (2,) not in lru and lru.match([1, 9]) == (1, "a2")

    def test_match_requires_strictly_shorter_prefix(self):
        lru = PrefixLRU(2)
        lru.put((1, 2, 3), "a")
        assert lru.match([1, 2, 3]) == (0, None)
        assert lru.match([1, 2, 3, 4]) == (3, "a")

    def test_zero_cap_disables(self):
        lru = PrefixLRU(0)
        lru.put((1,), "a")
        assert len(lru) == 0


# -- paged vs slot bit-identity ----------------------------------------------
class TestParity:
    def _pair(self, params, settings=None, **kw):
        st = settings or SamplerSettings(**GREEDY)
        return (BatchGenerator(CFG, params, settings=st, **kw),
                BatchGenerator(CFG, params, settings=st, kv_layout="paged",
                               **kw))

    def test_steady_batch_greedy_and_sampled(self, params):
        for st in (SamplerSettings(**GREEDY),
                   SamplerSettings(temperature=0.9, top_k=20, seed=11)):
            slot, paged = self._pair(params, settings=st)
            slot.set_prompts(PROMPTS)
            paged.set_prompts(PROMPTS)
            assert slot.generate(8) == paged.generate(8)

    def test_fused_blocks_and_adaptive_ladder(self, params):
        for kw in (dict(block_size=4),
                   dict(block_size=4, block_size_max=16),
                   dict(block_size=4, lookahead=True)):
            slot, paged = self._pair(params, **kw)
            slot.set_prompts(PROMPTS)
            paged.set_prompts(PROMPTS)
            assert slot.generate(9) == paged.generate(9), kw

    def test_midrun_admission_and_retire_reuse(self, params):
        outs = {}
        for layout in ("slot", "paged"):
            g = BatchGenerator(CFG, params,
                               settings=SamplerSettings(**GREEDY),
                               kv_layout=layout)
            g.set_prompts([[5, 9, 2, 11], [3, 1, 4, 1, 5, 9]])
            g.generate(4)
            g.enqueue([7, 7, 2], 5)        # mid-run admission
            g.generate(4)
            g.finish(0)                    # server-side retire
            g.enqueue([9, 9, 1, 4], 6)     # the freed slot is reused
            g.generate(4)
            outs[layout] = {s.stream_id: list(s.generated)
                            for s in g.streams if s.active}
        assert outs["slot"] == outs["paged"]

    def test_window_exhaustion_per_stream(self, params):
        cfg = tiny(max_seq_len=32)
        p = llama.init_params(cfg, jax.random.PRNGKey(5))
        res = {}
        for layout in ("slot", "paged"):
            g = BatchGenerator(cfg, p, settings=SamplerSettings(**GREEDY),
                               kv_layout=layout, kv_page_size=8)
            g.set_prompts([list(range(2, 28)), [5, 9, 2]])
            res[layout] = g.generate(20)
        assert res["slot"] == res["paged"]

    def test_int8_kv_pool(self, params):
        slot, paged = self._pair(params, kv_quant="int8")
        slot.set_prompts(PROMPTS)
        paged.set_prompts(PROMPTS)
        assert slot.generate(6) == paged.generate(6)

    def test_constrained_streams_ride_paged(self, params):
        from cake_tpu.constrain import (
            Guide,
            build_token_dfa,
            json_schema_to_regex,
        )

        cfg = tiny(max_seq_len=128)
        p = llama.init_params(cfg, jax.random.PRNGKey(7))

        class AsciiTok:
            def decode(self, ids):
                return "".join(chr(32 + (i % 95)) for i in ids)

            def encode(self, text):
                return [ord(c) - 32 for c in text]

        vocab = [AsciiTok().decode([i]) for i in range(cfg.vocab_size)]
        schema = {"type": "object",
                  "properties": {"a": {"type": "integer"},
                                 "ok": {"type": "boolean"}},
                  "required": ["a", "ok"]}

        def guide():
            return Guide(build_token_dfa(json_schema_to_regex(schema),
                                         vocab,
                                         eos_ids=(cfg.eos_token_id,)))

        outs = {}
        for layout in ("slot", "paged"):
            gen = BatchGenerator(cfg, p, tokenizer=AsciiTok(),
                                 settings=SamplerSettings(**GREEDY),
                                 kv_layout=layout)
            gen.set_prompts([[5, 6, 7], [8, 9, 10]],
                            guides=[None, guide()])
            out = gen.generate(40)
            gen.finish(0)
            gen.enqueue([11, 12, 13], 9, guide=guide())  # admitted guide
            for _ in range(120):
                gen.step()
                s9 = next((s for s in gen.streams if s.stream_id == 9),
                          None)
                if s9 is not None and s9.done:
                    break
            outs[layout] = (out, {s.stream_id: list(s.generated)
                                  for s in gen.streams if s.active})
        assert outs["slot"] == outs["paged"]
        # the constrained admitted stream really produced valid JSON
        gen9 = outs["paged"][1][9]
        text = AsciiTok().decode(
            [t for t in gen9 if t != cfg.eos_token_id])
        json.loads(text)


# -- sharing, eviction, deferral ---------------------------------------------
class TestSharing:
    def test_set_prompts_shared_prefix_shares_pages(self, params):
        prompts = [PREFIX + [5, 9], PREFIX + [7], PREFIX + [2, 4, 6]]
        slot = BatchGenerator(CFG, params,
                              settings=SamplerSettings(**GREEDY))
        paged = BatchGenerator(CFG, params,
                               settings=SamplerSettings(**GREEDY),
                               kv_layout="paged")
        slot.set_prompts(prompts)
        paged.set_prompts(prompts)
        assert slot.generate(6) == paged.generate(6)
        kp = paged.stats()["kvpool"]
        # 36-token prefix = 2 full 16-token pages, physically shared by
        # all 3 streams + the tree; the unaligned tail page is a private
        # copy-on-write materialization per stream
        assert kp["pages_shared"] == 2
        assert paged._pagepool.refcount(paged._tables[0][0]) == 4
        assert paged._tables[0][0] == paged._tables[1][0] \
            == paged._tables[2][0]
        assert paged._tables[0][2] != paged._tables[1][2]  # CoW boundary

    def test_admission_fanout_hits_and_shares(self, params):
        """The acceptance shape: n same-system-prompt arrivals through
        the admission path — prefix_hits >= n-1 (the SAME counter the
        gateway's prefix-affinity policy scores against) and physical
        pages shared, streams bit-identical to the slot layout."""
        n = 4
        outs = {}
        for layout in ("slot", "paged"):
            g = BatchGenerator(CFG, params,
                               settings=SamplerSettings(**GREEDY),
                               kv_layout=layout)
            g.set_prompts([[1]] * n)
            for s in g.streams:
                s.done = True
            for k, tail in enumerate(([5, 9], [7], [2, 4, 6], [8, 8])):
                g.enqueue(PREFIX + tail, 10 + k)
            outs[layout] = (_drive(g, want_tokens=6), g)
        assert outs["slot"][0] == outs["paged"][0]
        st = outs["paged"][1].stats()
        assert st["prefix_hits"] >= n - 1
        assert st["kvpool"]["pages_shared"] > 0

    def test_prefix_cache_disabled_skips_tree_but_batch_still_shares(
            self, params):
        """prefix_cache_entries=0 disables the prefix TREE (same contract
        as the slot store's '0 disables reuse') — no dead tree claims, no
        admission matching — but the batch's own shared-prefix pages are
        still one physical copy, freed when the last sharer retires
        (review regression)."""
        prompts = [PREFIX + [5, 9], PREFIX + [7]]
        g = BatchGenerator(CFG, params, settings=SamplerSettings(**GREEDY),
                           kv_layout="paged", prefix_cache_entries=0)
        ref = BatchGenerator(CFG, params,
                             settings=SamplerSettings(**GREEDY),
                             prefix_cache_entries=0)
        g.set_prompts(prompts)
        ref.set_prompts(prompts)
        assert g.generate(5) == ref.generate(5)
        st = g.stats()
        assert st["prefix_entries"] == 0          # tree never fed
        assert st["kvpool"]["pages_shared"] == 2  # batch still shares
        shared_pid = g._tables[0][0]
        assert g._pagepool.refcount(shared_pid) == 2  # streams only
        g.finish(g.streams[0].stream_id)
        g.finish(g.streams[1].stream_id)
        assert g._pagepool.refcount(shared_pid) == 0  # freed with them

    def test_retired_sharer_keeps_pages_alive_for_tree(self, params):
        g = BatchGenerator(CFG, params, settings=SamplerSettings(**GREEDY),
                           kv_layout="paged")
        g.set_prompts([[1], [1]])
        for s in g.streams:
            s.done = True
        g.enqueue(PREFIX + [5, 9], 10)
        _drive(g, want_tokens=4)
        g.finish(10)  # the only sharer retires; the tree keeps the pages
        g.enqueue(PREFIX + [7], 11)
        _drive(g, want_tokens=4)
        assert g.stats()["prefix_hits"] >= 1

    def test_eviction_under_pressure_and_deferral(self, params):
        # pool sized to the bare minimum (2 streams x 4 pages + sink ->
        # 16): prefix-tree claims must evict to keep admissions flowing
        g = BatchGenerator(CFG, params, settings=SamplerSettings(**GREEDY),
                           kv_layout="paged", kv_pool_pages=16)
        g.set_prompts([[1], [1]])
        for s in g.streams:
            s.done = True
        sid = 10
        for k in range(7):
            # distinct 35-token prompts: each stores 2 full pages in the
            # tree, so the accumulated chains must eventually evict to
            # keep admissions flowing through the 16-page pool
            g.enqueue([k + 40] + PREFIX[:32] + [k, 9], sid)
            _drive(g, want_tokens=3)
            g.finish(sid)
            sid += 1
        assert g._pagepool.free_count > 0
        assert g._pagepool._evict_ctr.value > 0

    def test_pool_sizing_validation(self, params):
        g = BatchGenerator(CFG, params, settings=SamplerSettings(**GREEDY),
                           kv_layout="paged", kv_pool_pages=8)
        with pytest.raises(ValueError, match="kv_pool_pages"):
            g.set_prompts(PROMPTS)  # 3 streams x 4 pages + sink > 8

    def test_constructor_validation(self, params):
        with pytest.raises(ValueError, match="paged"):
            BatchGenerator(CFG, params, kv_layout="paged", spec_k=4)
        with pytest.raises(ValueError, match="kv_page_size"):
            BatchGenerator(CFG, params, kv_layout="paged", kv_page_size=7)
        with pytest.raises(ValueError, match="kv_layout"):
            BatchGenerator(CFG, params, kv_layout="blocks")
        # a malformed pool size fails AT CONSTRUCTION (where the CLI's
        # ValueError guard makes it a clean exit), not at set_prompts
        # (review regression); only the batch-dependent bound waits
        with pytest.raises(ValueError, match="power of two"):
            BatchGenerator(CFG, params, kv_layout="paged",
                           kv_pool_pages=100)


# -- no-retrace pin ----------------------------------------------------------
class TestCompilePin:
    def test_page_table_churn_never_retraces(self, params):
        """Page-table updates (growth across boundaries, admission,
        retirement) are DATA, not shapes: the paged decode program's
        compile count matches the slot layout's under the identical
        drive, and stays flat once the admission path has run once."""
        counts = {}
        for layout in ("slot", "paged"):
            g = BatchGenerator(CFG, params,
                               settings=SamplerSettings(**GREEDY),
                               kv_layout=layout)
            g.set_prompts([[5, 9, 2, 11], [3, 1, 4, 1, 5, 9]])
            g.generate(20)  # crosses the 16-token page boundary
            sizes = [g._decode_single_jit._cache_size()]
            for k in range(3):
                for s in g.streams:
                    s.done = True
                g.enqueue([3 + k, 5, 9, 2], 100 + k)
                _drive(g, want_tokens=3)
                sizes.append(g._decode_single_jit._cache_size())
            counts[layout] = sizes
        assert counts["paged"] == counts["slot"]
        # flat after the first admission cycle: later admissions, page
        # allocations and retirements add ZERO compiles
        assert counts["paged"][1] == counts["paged"][-1]

    def test_masked_paged_program_pinned_like_slot(self, params):
        """The masked (constrained) decode program: a second grammar, a
        guide attached through the admission path, and paged page-table
        churn add no compiles beyond what the SLOT layout pays under the
        identical drive — and a fresh same-shape batch adds none at all
        (the per-shape pin of the constrain suite, on paged)."""
        from cake_tpu.constrain import Guide, build_token_dfa

        cfg = tiny(max_seq_len=64)
        p = llama.init_params(cfg, jax.random.PRNGKey(7))
        vocab = [chr(32 + (i % 95)) for i in range(cfg.vocab_size)]
        d1 = build_token_dfa("[0-9]{1,8}", vocab,
                             eos_ids=(cfg.eos_token_id,))
        d2 = build_token_dfa("[a-f]{1,6}", vocab,
                             eos_ids=(cfg.eos_token_id,))
        counts = {}
        for layout in ("slot", "paged"):
            g = BatchGenerator(cfg, p, settings=SamplerSettings(**GREEDY),
                               kv_layout=layout)
            g.set_prompts([[5, 6, 7], [8, 9, 10]],
                          guides=[Guide(d1), None])
            g.generate(6)
            c1 = g._masked_jit._cache_size()
            g.finish(0)
            g.enqueue([5, 6, 7], 9, guide=Guide(d2))  # admission splice
            _drive(g, want_tokens=4)
            c2 = g._masked_jit._cache_size()
            # a different grammar in a FRESH same-shape batch: no compile
            g.set_prompts([[5, 6, 7], [8, 9, 10]],
                          guides=[None, Guide(d2)])
            g.generate(4)
            counts[layout] = (c1, c2, g._masked_jit._cache_size())
            assert counts[layout][2] == counts[layout][1]
        assert counts["paged"] == counts["slot"]


# -- serving plane + churn workload ------------------------------------------
class TestServe:
    @pytest.fixture(scope="class")
    def paged_server(self, params):
        from cake_tpu.serve.api import start_api_server
        from cake_tpu.serve.scheduler import Scheduler

        cfg = tiny(max_seq_len=64, eos_token_id=-1)
        p = llama.init_params(cfg, jax.random.PRNGKey(7))
        gen = BatchGenerator(cfg, p, settings=SamplerSettings(**GREEDY),
                             kv_layout="paged")
        sched = Scheduler(gen, queue_depth=8, request_timeout_s=120)
        sched.start(max_concurrent=2, warm_prompt_len=8)
        srv = start_api_server(sched)
        yield srv
        srv.close()
        sched.close()

    def test_healthz_reports_pool_pressure(self, paged_server):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{paged_server.port}/healthz",
                timeout=30) as r:
            body = json.loads(r.read())
        assert body["ok"] and "kv_pages_free" in body
        assert body["kv_pages_free"] > 0

    def test_loadgen_churn_workload_over_paged_server(self, paged_server):
        """The churn regime over real HTTP: Poisson arrivals, short/long
        prompt mix, early disconnects — the paged server reaps
        disconnected slots and completes everything else."""
        from cake_tpu.tools.loadgen import run_load

        stats = run_load(f"http://127.0.0.1:{paged_server.port}", n=8,
                         max_tokens=12, vocab=CFG.vocab_size,
                         seed=3, timeout=120.0, workload="churn",
                         rate=6.0, prompt_lens=[4, 20],
                         disconnect_every=3)
        assert stats["errors"] == 0
        assert stats["disconnected"] >= 2   # every 3rd of 8 walked away
        assert stats["completed"] == 8      # disconnects still streamed

    def test_churn_disconnect_zero_really_disables(self, paged_server):
        """--disconnect-every 0 under the churn workload means NEVER, as
        the help promises — 0 must not be mistaken for the unset sentinel
        that triggers the churn default of 4 (review regression)."""
        from cake_tpu.tools.loadgen import run_load

        stats = run_load(f"http://127.0.0.1:{paged_server.port}", n=4,
                         max_tokens=6, vocab=CFG.vocab_size, seed=5,
                         timeout=120.0, workload="churn", rate=8.0,
                         prompt_lens=[4], disconnect_every=0)
        assert stats["errors"] == 0 and stats["disconnected"] == 0

    def test_churn_workload_validation(self):
        from cake_tpu.tools.loadgen import run_load

        with pytest.raises(ValueError, match="churn"):
            run_load("http://127.0.0.1:1", n=1, workload="churn",
                     stream=False)
        with pytest.raises(ValueError, match="workload"):
            run_load("http://127.0.0.1:1", n=1, workload="nope")
