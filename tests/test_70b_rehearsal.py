"""70B-on-16 dress rehearsal (BASELINE.md configs 4/5).

Three planes, no pod required:
- divisibility: the real Llama-3-70B geometry shards onto the v5e-16 layouts
  of record (validate_shardable);
- HBM budget: the per-chip arithmetic (utils.memory.hbm_budget) shows bf16
  does NOT fit a 16 GiB chip at the serving window while int8 does — the
  SURVEY §7 "int8 is load-bearing" claim, now checkable;
- execution: an 80-layer model (tiny dims, the 70B layer/stage geometry)
  runs prefill + decode on a 16-virtual-device CPU mesh at stage=16 and
  stage=8 x tp=2, int8-quantized, matching the single-device oracle
  token-for-token.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

from cake_tpu.models.config import llama3_70b
from cake_tpu.parallel.mesh import validate_shardable
from cake_tpu.utils.memory import hbm_budget

REPO = Path(__file__).resolve().parents[1]
V5E_USABLE = 14.5 * 2**30  # 16 GiB HBM minus ~1.5 GiB runtime reserve (measured)


@pytest.mark.parametrize(
    "stages,tp,sp",
    [(16, 1, 1), (8, 2, 1), (4, 4, 1), (16, 1, 2), (8, 2, 2)],
)
def test_70b_divisibility_on_16(stages, tp, sp):
    """80 layers / 64 heads / 8 kv heads / 28672 intermediate divide into
    every 16-chip layout of record."""
    validate_shardable(llama3_70b(max_seq_len=8192), stages, tp, sp)


def test_70b_hbm_budget_configs_4_and_5():
    """Config 4 (bf16) vs config 5 (int8) on v5e-16 at an 8K window
    (numbers documented in BASELINE.md).

    bf16 per chip: 5 layers x 1.6 GiB + 2 GiB replicated embed + 2 GiB
    lm_head + KV = ~12 GiB — fits the ~14.5 GiB usable, but with only
    ~2.5 GiB for activations/workspace/fragmentation. int8 (config 5)
    halves the linears to ~7.1 GiB — the comfortable serving tier, and the
    one that leaves room to grow batch/window.
    """
    cfg = llama3_70b(max_seq_len=8192)
    bf16 = hbm_budget(cfg, num_stages=16, tp=1)
    int8 = hbm_budget(cfg, num_stages=16, tp=1, quant="int8")
    assert bf16["total"] < V5E_USABLE, "bf16 70B/16 fits, tightly"
    assert bf16["total"] > 0.75 * V5E_USABLE, "…with little headroom"
    assert int8["total"] < 0.55 * V5E_USABLE, "int8 70B/16 fits comfortably"
    # KV at the full window stays a minor term in this layout
    assert int8["kv_cache"] < 0.5 * 2**30
    # config 5 with tp=2 x stage=8 also fits (lm_head/linears shard further,
    # embed replication is the floor)
    int8_tp2 = hbm_budget(cfg, num_stages=8, tp=2, quant="int8")
    assert int8_tp2["total"] < 0.55 * V5E_USABLE
    # serving tier at batch 32 / 8K window: the int8 KV cache returns
    # multi-GiB of per-chip headroom that bf16 KV burns
    bf16_kv = hbm_budget(cfg, num_stages=16, tp=1, quant="int8", batch=32)
    int8_kv = hbm_budget(cfg, num_stages=16, tp=1, quant="int8", batch=32,
                         cache_bytes_per_el=1)
    assert bf16_kv["total"] - int8_kv["total"] > 2.0 * 2**30
    assert int8_kv["total"] < 0.75 * V5E_USABLE


_SCRIPT = r"""
import jax
from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.quant import quantize_params
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.runtime.mesh_generator import MeshGenerator

assert len(jax.devices()) == 16, jax.devices()
cfg = tiny(num_hidden_layers=80, max_seq_len=64)
params = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(0)))
settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
g_local = LlamaGenerator(cfg, params, settings=settings)
g_local.set_prompt([5, 9, 2, 11])
want = [g_local.next_token(i).id for i in range(6)]
for stages, tp in ((16, 1), (8, 2)):
    g = MeshGenerator(cfg, params, settings=settings, num_stages=stages, tp=tp)
    g.set_prompt([5, 9, 2, 11])
    got = [g.next_token(i).id for i in range(6)]
    assert got == want, (stages, tp, got, want)
    print(f"stage={stages} tp={tp} ok", flush=True)
# config-5 serving tier: int8 weights + int8 KV on the 16-stage layout,
# parity with the single-device int8-KV oracle
g_local8 = LlamaGenerator(cfg, params, settings=settings, kv_quant="int8")
g_local8.set_prompt([5, 9, 2, 11])
want8 = [g_local8.next_token(i).id for i in range(6)]
g8 = MeshGenerator(cfg, params, settings=settings, num_stages=16,
                   kv_quant="int8")
g8.set_prompt([5, 9, 2, 11])
got8 = [g8.next_token(i).id for i in range(6)]
assert got8 == want8, (got8, want8)
print("stage=16 int8-kv ok", flush=True)
print("70b-geometry rehearsal ok")
"""


def test_70b_geometry_runs_on_16_device_mesh():
    """80 layers, int8, stage=16 and stage=8 x tp=2 on 16 virtual CPU
    devices: prefill + 6 decode tokens, greedy parity with the single-device
    oracle. (Subprocess: the suite's own mesh is pinned to 8 devices.)"""
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=16"]
    )
    r = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        capture_output=True, text=True, timeout=540, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    assert "70b-geometry rehearsal ok" in r.stdout
