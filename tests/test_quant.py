"""Int8 weight quantization: round-trip accuracy, kernel parity, model
quality, and sharded execution of quantized params."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops import quant
from cake_tpu.ops.kvcache import init_cache
from cake_tpu.ops.pallas.quant import quant_matmul_pallas
from cake_tpu.ops.quant import (
    QuantizedLinear,
    dense,
    dequantize_linear,
    quantize_linear,
    quantize_params,
)


def test_quantize_round_trip():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
    ql = quantize_linear(w)
    assert ql.q.dtype == jnp.int8 and ql.scale.shape == (32,)
    back = dequantize_linear(ql, jnp.float32)
    # max error bounded by half a quantization step per channel
    step = np.asarray(ql.scale)
    err = np.abs(np.asarray(back) - np.asarray(w))
    assert (err <= 0.5 * step[None, :] + 1e-7).all()


def test_quantize_stacked_scale_axes():
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 16, 8), jnp.float32)
    ql = quantize_linear(w)
    assert ql.q.shape == (3, 16, 8)
    assert ql.scale.shape == (3, 8)


def test_quant_matmul_pallas_matches_xla():
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (8, 64), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (64, 32), jnp.float32)
    ql = quantize_linear(w)
    ref = quant.quant_matmul_xla(x, ql.q, ql.scale)
    out = quant_matmul_pallas(x, ql.q, ql.scale, block_m=4, block_n=8,
                              block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_dense_dispatch():
    x = jnp.ones((2, 8), jnp.float32)
    w = jnp.ones((8, 4), jnp.float32)
    np.testing.assert_allclose(np.asarray(dense(x, w)), 8.0)
    out = dense(x, quantize_linear(w))
    np.testing.assert_allclose(np.asarray(out), 8.0, rtol=1e-2)


@pytest.fixture(scope="module")
def cfg():
    return tiny(max_seq_len=32)


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(cfg, jax.random.PRNGKey(0))


def test_quantized_model_logits_close(cfg, params):
    qparams = quantize_params(params)
    assert isinstance(qparams["layers"]["wq"], QuantizedLinear)
    assert isinstance(qparams["lm_head"], QuantizedLinear)
    assert not isinstance(qparams["layers"]["attn_norm"], QuantizedLinear)
    ids = [3, 1, 4, 1, 5, 9, 2, 6]
    tokens = jnp.asarray([ids], jnp.int32)
    logits_f, _ = llama.forward(
        params, tokens, init_cache(cfg, 1, cfg.max_seq_len), 0, cfg
    )
    logits_q, _ = llama.forward(
        qparams, tokens, init_cache(cfg, 1, cfg.max_seq_len), 0, cfg
    )
    a = np.asarray(logits_f[0], np.float64)
    b = np.asarray(logits_q[0], np.float64)
    cos = a @ b / (np.linalg.norm(a) * np.linalg.norm(b))
    assert cos > 0.99, f"cosine similarity {cos}"


def test_quantized_generation_runs(cfg, params):
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    g = LlamaGenerator(cfg, quantize_params(params),
                       settings=SamplerSettings(temperature=0.0))
    g.set_prompt([3, 1, 4])
    ids = [g.next_token(i).id for i in range(6)]
    assert len(ids) == 6
    assert all(0 <= t < cfg.vocab_size for t in ids)


def test_quantize_during_load_matches_posthoc(cfg, params, tmp_path):
    """load_llama_params(quantize='int8') (host-side, streaming) produces the
    same pytree as loading bf16 then quantize_params."""
    from cake_tpu.utils.weights import load_llama_params, save_llama_params

    save_llama_params(params, tmp_path)
    loaded_q = load_llama_params(
        tmp_path, cfg.num_hidden_layers, dtype="float32", quantize="int8"
    )
    posthoc = quantize_params(
        load_llama_params(tmp_path, cfg.num_hidden_layers, dtype="float32")
    )
    for name in ("wq", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(loaded_q["layers"][name].q),
            np.asarray(posthoc["layers"][name].q),
        )
        np.testing.assert_allclose(
            np.asarray(loaded_q["layers"][name].scale),
            np.asarray(posthoc["layers"][name].scale), rtol=1e-6,
        )
    np.testing.assert_array_equal(
        np.asarray(loaded_q["lm_head"].q), np.asarray(posthoc["lm_head"].q)
    )
    # norms/embed stay plain
    assert not isinstance(loaded_q["layers"]["attn_norm"], QuantizedLinear)
    assert not isinstance(loaded_q["embed"], QuantizedLinear)


def test_quantized_sharded_pipeline(cfg, params):
    """Quantized params shard over (stage, tp) and decode in one program."""
    from cake_tpu.ops import sampling
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.parallel.mesh import MeshPlan, shard_cache, shard_params
    from cake_tpu.parallel.pipeline import build_sharded_decode

    qparams = quantize_params(params)
    plan = MeshPlan.build(cfg, num_stages=2, tp=2)
    sp = shard_params(qparams, plan.mesh)
    settings = SamplerSettings(temperature=0.0)
    decode = build_sharded_decode(cfg, settings, plan, params_like=qparams)
    cache = shard_cache(init_cache(cfg, 1, cfg.max_seq_len), plan.mesh)
    history, hist_slot = sampling.init_history(settings.repeat_last_n)
    tok, cache, history, hist_slot = decode(
        sp, jnp.asarray([5], jnp.int32), cache, jnp.int32(0),
        jax.random.PRNGKey(0), history[None, :], hist_slot,
    )
    # parity with the unsharded quantized model
    logits_ref, _ = llama.forward(
        qparams, jnp.asarray([[5]], jnp.int32),
        init_cache(cfg, 1, cfg.max_seq_len), 0, cfg,
    )
    assert int(tok[0]) == int(jnp.argmax(logits_ref[0]))


def test_quantized_block_decode_matches_single(cfg, params):
    """int8 weights + fused multi-step decode: the blocked stream equals the
    single-step quantized stream (quant.dense inside lax.scan)."""
    from cake_tpu.ops.quant import quantize_params
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    qp = quantize_params(params)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    a = LlamaGenerator(cfg, qp, settings=settings)
    a.set_prompt([5, 9, 2])
    single = [a.next_token(i).id for i in range(9)]
    b = LlamaGenerator(cfg, qp, settings=settings, block_size=4)
    b.set_prompt([5, 9, 2])
    assert [b.next_token(i).id for i in range(9)] == single


# -- instance-pinned backend (bucket-invariant int8 serving) ------------------

def test_pinned_impl_overrides_auto_gate():
    """quant_matmul under a pin uses the pinned backend regardless of row
    count; outside the pin the measured m-gate applies."""
    x1 = jax.random.normal(jax.random.PRNGKey(0), (1, 256), jnp.bfloat16)
    x32 = jax.random.normal(jax.random.PRNGKey(1), (32, 256), jnp.bfloat16)
    w = quantize_linear(
        jax.random.normal(jax.random.PRNGKey(2), (256, 256), jnp.float32))
    y_xla_1 = quant.quant_matmul(x1, w.q, w.scale, impl="xla")
    y_pal_32 = quant.quant_matmul(x32, w.q, w.scale, impl="pallas")
    with quant.pinned_impl("xla"):
        np.testing.assert_array_equal(
            quant.quant_matmul(x1, w.q, w.scale), y_xla_1)
        np.testing.assert_array_equal(
            quant.quant_matmul(x32, w.q, w.scale),
            quant.quant_matmul(x32, w.q, w.scale, impl="xla"))
    with quant.pinned_impl("pallas"):
        np.testing.assert_array_equal(
            quant.quant_matmul(x32, w.q, w.scale), y_pal_32)
    assert quant.pinned() is None  # context restored


def test_int8_serving_streams_bucket_invariant():
    """The r3 caveat, closed: the same stream (same stream_id, prompt,
    seed) served from a batch-4 vs a batch-8 int8 instance emits IDENTICAL
    sampled tokens — both instances pin one matmul backend at first
    set_prompts, so no batch-size bucket or admission geometry can flip a
    near-boundary token (r3 verdict item 10)."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    cfg = tiny(max_seq_len=64, eos_token_id=-1)
    qparams = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(4)))
    settings = SamplerSettings(temperature=0.9, top_k=12, seed=11)
    target = [5, 9, 2, 7, 1]
    fillers = [[3, 3, 1], [8, 2, 6, 4], [1, 1], [9, 9, 9],
               [2, 4, 6], [7, 7], [5, 1, 5]]

    def stream0(batch):
        gen = BatchGenerator(cfg, qparams, settings=settings)
        prompts = [list(target)] + [list(f) for f in fillers[: batch - 1]]
        gen.set_prompts(prompts, stream_ids=list(range(100, 100 + batch)))
        out = []
        for _ in range(6):
            row = gen.step()
            if row[0] is not None:
                out.append(int(row[0].id) if hasattr(row[0], "id")
                           else int(row[0]))
        assert gen._quant_pin == "xla"  # below the m>=16 crossover
        return out

    assert stream0(4) == stream0(8)


def test_explicit_backend_pin_spans_crossover_instances():
    """Instances on OPPOSITE sides of the m>=16 crossover pin different
    backends by default (documented residual); an explicit quant_backend=
    makes a batch-4 and a batch-16 instance share one backend so the same
    stream is bit-identical across them."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    cfg = tiny(max_seq_len=64, eos_token_id=-1)
    qparams = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(4)))
    settings = SamplerSettings(temperature=0.9, top_k=12, seed=11)
    target = [5, 9, 2, 7, 1]

    def stream0(batch, backend):
        gen = BatchGenerator(cfg, qparams, settings=settings,
                             quant_backend=backend)
        prompts = [list(target)] + [[2 + (i * 3) % 7, 4, 1]
                                    for i in range(batch - 1)]
        gen.set_prompts(prompts, stream_ids=list(range(100, 100 + batch)))
        assert gen._quant_pin == backend
        out = []
        for _ in range(5):
            row = gen.step()
            if row[0] is not None:
                out.append(int(row[0].id) if hasattr(row[0], "id")
                           else int(row[0]))
        return out

    assert stream0(4, "xla") == stream0(16, "xla")


def test_pin_crosses_to_pallas_at_16_local_rows():
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    cfg = tiny(max_seq_len=32, eos_token_id=-1)
    qparams = quantize_params(llama.init_params(cfg, jax.random.PRNGKey(4)))
    gen = BatchGenerator(cfg, qparams,
                         settings=SamplerSettings(temperature=0.0))
    gen.set_prompts([[1 + i % 5, 2, 3] for i in range(16)])
    gen.step()
    assert gen._quant_pin == "pallas"


def test_pin_is_isolated_across_threads():
    """The backend pin is a ContextVar: two threads holding different pins
    (two serving instances dispatching concurrently) never observe each
    other's value."""
    import threading

    seen = {}
    barrier = threading.Barrier(2, timeout=10)

    def worker(name, pin):
        with quant.pinned_impl(pin):
            barrier.wait()          # both pins active simultaneously
            seen[name] = quant.pinned()
            barrier.wait()
    t1 = threading.Thread(target=worker, args=("a", "xla"))
    t2 = threading.Thread(target=worker, args=("b", "pallas"))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert seen == {"a": "xla", "b": "pallas"}
    assert quant.pinned() is None
