"""SLO-aware scheduling (cake_tpu/serve, ISSUE 20): priority classes,
preemption with host-RAM KV spill, per-tenant fairness.

`make slo-smoke` acceptance: an interactive arrival jumps queued batch
work and — on a saturated paged engine — preempts a batch victim into
the bounded host-RAM spill store, with the victim's stream resuming
BIT-IDENTICALLY (greedy, sampled, and constrained mid-grammar) when
pressure drops; the spill chaos matrix (resume-storm, spill-store-full,
victim-finishes-during-spill) leaves every stream intact; admission
deferral under spill pressure counts exactly once per deferred
admission; unknown ``class``/``tenant`` values 400 at the serve plane
and classed requests ride through the gateway untouched; ``/v1/batch``
runs N prompts to one resumable JSON result set; and over-budget
tenants queue behind in-budget arrivals of the same class.
"""

import contextlib
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from cake_tpu.disagg import peek_xfer_id
from cake_tpu.gateway.api import start_gateway
from cake_tpu.gateway.health import Backend, HealthMonitor
from cake_tpu.gateway.policy import make_policy, pick_batch
from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.serve.api import start_api_server
from cake_tpu.serve.scheduler import THROTTLED, Scheduler
from cake_tpu.serve.spill import SpillFull, SpillStore
from cake_tpu.testing.chaos import (
    SpillChaos,
    SpillFault,
    spill_schedule_from_seed,
)

# eos disabled (-1 never sampled): deterministic stream lengths, so the
# preempt/resume round trips can compare exact token sequences
CFG = tiny(max_seq_len=64, eos_token_id=-1)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)
SAMPLED = dict(temperature=0.9, seed=5)

# the canonical preemption victim: long enough that an interactive
# arrival injected after its first tokens always finds it mid-decode
VICTIM = {"prompt": "abcd", "max_tokens": 32, "class": "batch"}
INTERACTIVE = {"prompt": "zz", "max_tokens": 4, "class": "interactive"}


class _FakeTok:
    """id -> letter (alnum decodes, the test_serve convention)."""

    def decode(self, ids):
        return "".join(chr(ord("a") + (i % 26)) for i in ids)

    def encode(self, text):
        return [ord(c) - ord("a") for c in text]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(7))


def _egen(params, pool=None, tokenizer=None, **settings):
    """Bare paged engine (no serve stack) for the admit-defer test."""
    kw = {"kv_pool_pages": pool} if pool else {}
    return BatchGenerator(
        CFG, params, tokenizer=tokenizer,
        settings=SamplerSettings(**(settings or GREEDY)),
        kv_layout="paged", kv_page_size=16, **kw)


def _tokens(gen, sid):
    for s in gen.streams:
        if s.active and not s.done and s.stream_id == sid:
            return list(s.generated)
    return None


def _drive(gen, sid, want, max_steps=400):
    """step() until stream ``sid`` holds ``want`` tokens; returns them."""
    for _ in range(max_steps):
        got = _tokens(gen, sid)
        if got is not None and len(got) >= want \
                and not gen.pending_admissions():
            return got[:want]
        gen.step()
    raise AssertionError(f"stream {sid} never reached {want} tokens")


@contextlib.contextmanager
def _stack(params, *, max_concurrent=1, queue_depth=16, settings=None,
           **sched_kw):
    """One paged serve replica: engine + scheduler + HTTP API. ONE slot
    by default — preemption needs a saturated engine, and one slot makes
    "saturated" deterministic."""
    gen = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                         settings=SamplerSettings(**(settings or GREEDY)),
                         kv_layout="paged", kv_page_size=16)
    sched = Scheduler(gen, queue_depth=queue_depth, request_timeout_s=120,
                      **sched_kw)
    sched.start(max_concurrent=max_concurrent)
    srv = start_api_server(sched)
    try:
        yield srv, sched
    finally:
        srv.close()
        sched.close()


def _url(srv) -> str:
    return f"http://127.0.0.1:{srv.port}"


def _get(url: str, timeout: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post(srv_or_url, body: dict, path: str = "/v1/completions",
          timeout: float = 120.0):
    base = srv_or_url if isinstance(srv_or_url, str) else _url(srv_or_url)
    req = urllib.request.Request(
        base.rstrip("/") + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _post_sse(srv_or_url, body: dict, timeout: float = 120.0,
              on_event=None):
    """Stream one request; returns (parsed events, raw data-line bytes)."""
    base = srv_or_url if isinstance(srv_or_url, str) else _url(srv_or_url)
    body = dict(body, stream=True)
    req = urllib.request.Request(
        base.rstrip("/") + "/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    events, raw_lines = [], []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        for raw in r:
            raw = raw.strip()
            if not raw.startswith(b"data: "):
                continue
            raw_lines.append(raw)
            data = raw[len(b"data: "):]
            ev = data.decode() if data == b"[DONE]" else json.loads(data)
            events.append(ev)
            if on_event:
                on_event(ev)
    return events, raw_lines


def _ids_of(events):
    return [e["token"] for e in events
            if isinstance(e, dict) and "token" in e]


def _wait_queued(srv, n, timeout=30.0):
    """Poll /healthz until >= n requests sit in the admission queue —
    the ordering tests need BOTH contenders queued while the slot
    holder is still running, or there is nothing to reorder."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if _get(_url(srv) + "/healthz")["queued"] >= n:
            return True
        time.sleep(0.01)
    return False


def _preempt_run(srv, victim_body, interactive=None, n_before=2):
    """Start the victim stream, inject an interactive arrival once the
    victim is mid-decode (``n_before`` tokens seen), run the arrival to
    completion, then drain the victim. Returns (victim token ids,
    interactive unary result)."""
    state = {"n": 0}
    mid_decode = threading.Event()

    def on_event(ev):
        if isinstance(ev, dict) and "token" in ev:
            state["n"] += 1
            if state["n"] >= n_before:
                mid_decode.set()

    def run():
        state["events"], _ = _post_sse(srv, victim_body,
                                       on_event=on_event)

    t = threading.Thread(target=run)
    t.start()
    assert mid_decode.wait(60), "victim never reached steady decode"
    res = _post(srv, interactive or INTERACTIVE)
    t.join(timeout=120)
    assert not t.is_alive(), "victim stream never completed"
    return _ids_of(state["events"]), res


@pytest.fixture(scope="module")
def greedy_base(params):
    """The victim's unpreempted greedy stream — the bit-identity
    reference every preemption/chaos case compares against."""
    with _stack(params) as (srv, _):
        events, _ = _post_sse(srv, VICTIM)
    ids = _ids_of(events)
    assert len(ids) == VICTIM["max_tokens"]
    return ids


@pytest.fixture(scope="module")
def server(params):
    """Shared 2-slot replica for the API-surface tests (validation,
    healthz, /v1/batch) — nothing here depends on preemption timing."""
    gen = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                         settings=SamplerSettings(**GREEDY),
                         kv_layout="paged", kv_page_size=16)
    sched = Scheduler(gen, queue_depth=16, request_timeout_s=120)
    sched.start(max_concurrent=2)
    srv = start_api_server(sched)
    yield srv
    srv.close()
    sched.close()


# -- spill store + chaos units (no engine) -----------------------------------


class TestSpillStore:
    def test_claim_lifecycle_and_capacity(self):
        st = SpillStore(max_bytes=100)
        c = st.spill_begin("a", 60, pages=2)
        # reservations count against capacity before the payload lands
        with pytest.raises(SpillFull):
            st.spill_begin("b", 60, pages=1)
        with pytest.raises(ValueError):
            st.spill_begin("a", 10, pages=1)  # duplicate key
        st.spill_commit(c, b"x" * 60)
        assert len(st) == 1
        assert st.stats()["bytes"] == 60 and st.stats()["pages"] == 2
        # abort releases the reservation for the next claim
        st.spill_abort(st.spill_begin("b", 40, pages=1))
        c2 = st.spill_begin("b", 40, pages=1)
        st.spill_commit(c2, b"y" * 40)
        assert st.take("a") == b"x" * 60
        assert st.take("a") is None  # take pops
        assert st.discard("b") and not st.discard("b")
        assert len(st) == 0

    def test_rejects_bad_capacity(self):
        with pytest.raises(ValueError):
            SpillStore(max_bytes=0)

    def test_commit_without_claim_raises(self):
        st = SpillStore(max_bytes=100)
        c = st.spill_begin("a", 10, pages=1)
        st.spill_abort(c)
        with pytest.raises(ValueError):
            st.spill_commit(c, b"z" * 10)


class TestSpillChaos:
    def test_fault_fires_at_exact_consult(self):
        c = SpillChaos([SpillFault("spill_full", at=2)])
        assert not c.fire("spill_full")   # consult 1: not yet
        assert c.fire("spill_full")       # consult 2: fires (and pops)
        assert not c.fire("spill_full")   # consult 3: spent
        assert c.events == [("spill_full@2", 2)]

    def test_kind_validation_and_seeded_schedule(self):
        with pytest.raises(ValueError):
            SpillFault("bogus", 1)
        with pytest.raises(ValueError):
            SpillFault("spill_full", 0)
        a, b = spill_schedule_from_seed(7), spill_schedule_from_seed(7)
        assert a == b and len(a) == 3
        assert all(f.kind != "none" and f.at >= 1 for f in a)
        assert spill_schedule_from_seed(8) != a


# -- request validation + surfaces -------------------------------------------


def test_class_and_tenant_validation(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, {"prompt": "ab", "max_tokens": 2,
                       "class": "premium"})
    assert e.value.code == 400
    for bad in (7, "", "x" * 65):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(server, {"prompt": "ab", "max_tokens": 2,
                           "tenant": bad})
        assert e.value.code == 400
    out = _post(server, {"prompt": "ab", "max_tokens": 2,
                         "class": "batch", "tenant": "acme"})
    assert len(out["token_ids"]) == 2


def test_healthz_and_metrics_carry_spill_series(server):
    h = _get(_url(server) + "/healthz")
    assert h["spilled"] == 0 and h["preemptions"] == 0
    text = urllib.request.urlopen(
        _url(server) + "/metrics", timeout=30).read().decode()
    for series in ("cake_serve_preemptions", "cake_serve_spill_bytes",
                   "cake_serve_tenant_throttled"):
        assert series in text, f"/metrics missing {series}"


def test_pick_batch_counts_spilled_load():
    """The batch-class route is least-outstanding-work-per-slot, and a
    replica's spilled victims are outstanding work: they come back."""
    a = Backend("pb0", "127.0.0.1:9991")
    b = Backend("pb1", "127.0.0.1:9992")
    a.probe_ok({"queued": 1, "running": 0, "max_concurrent": 2},
               up_after=1)
    b.probe_ok({"queued": 0, "running": 0, "max_concurrent": 2},
               up_after=1)
    assert pick_batch([a, b]) is b
    b.probe_ok({"queued": 0, "running": 0, "max_concurrent": 2,
                "spilled": 4}, up_after=1)
    assert pick_batch([a, b]) is a


# -- class-priority admission ordering ---------------------------------------


def test_interactive_jumps_queued_batch(params):
    """spill_mb=0: class ordering WITHOUT preemption — the queued
    interactive arrival must still finish before the batch request that
    arrived ahead of it."""
    with _stack(params, spill_mb=0.0) as (srv, sched):
        assert sched.stats().get("spill") is None
        first_token = threading.Event()
        occ = threading.Thread(target=_post_sse, args=(
            srv, {"prompt": "abcd", "max_tokens": 48,
                  "class": "interactive"}),
            kwargs={"on_event": lambda ev: first_token.set()})
        occ.start()
        assert first_token.wait(60)
        order, lock = [], threading.Lock()

        def client(name, body):
            _post(srv, body)
            with lock:
                order.append(name)

        tb = threading.Thread(target=client, args=(
            "batch", {"prompt": "bb", "max_tokens": 2, "class": "batch"}))
        ti = threading.Thread(target=client, args=(
            "inter", {"prompt": "ii", "max_tokens": 2,
                      "class": "interactive"}))
        tb.start()
        assert _wait_queued(srv, 1)  # batch queues first...
        ti.start()
        assert _wait_queued(srv, 2)  # ...and interactive must jump it
        for t in (tb, ti, occ):
            t.join(timeout=120)
            assert not t.is_alive()
        assert order[0] == "inter", f"batch served first: {order}"


def test_fifo_policy_keeps_arrival_order(params):
    with _stack(params, sched_policy="fifo") as (srv, sched):
        assert sched.stats()["sched_policy"] == "fifo"
        first_token = threading.Event()
        occ = threading.Thread(target=_post_sse, args=(
            srv, {"prompt": "abcd", "max_tokens": 48,
                  "class": "interactive"}),
            kwargs={"on_event": lambda ev: first_token.set()})
        occ.start()
        assert first_token.wait(60)
        order, lock = [], threading.Lock()

        def client(name, body):
            _post(srv, body)
            with lock:
                order.append(name)

        tb = threading.Thread(target=client, args=(
            "batch", {"prompt": "bb", "max_tokens": 2, "class": "batch"}))
        ti = threading.Thread(target=client, args=(
            "inter", {"prompt": "ii", "max_tokens": 2,
                      "class": "interactive"}))
        tb.start()
        assert _wait_queued(srv, 1)
        ti.start()
        assert _wait_queued(srv, 2)
        for t in (tb, ti, occ):
            t.join(timeout=120)
        assert order[0] == "batch", f"fifo reordered arrivals: {order}"
        with pytest.raises(ValueError, match="sched_policy"):
            sched.set_policy("lifo")


# -- preemption + spill round trips (the tentpole) ---------------------------


def test_preempt_resume_bit_identical_greedy(params, greedy_base):
    with _stack(params) as (srv, sched):
        ids, res = _preempt_run(srv, VICTIM)
        st = sched.stats()
        assert st["preemptions"] >= 1, "interactive never preempted"
        assert st["spilled"] == 0, "victim left in the spill store"
        assert st["sched_policy"] == "slo"
        assert st["spill"]["streams"] == 0
        assert st["spill"]["max_bytes"] == 64 << 20
        assert len(res["token_ids"]) == INTERACTIVE["max_tokens"]
        assert ids == greedy_base
        h = _get(_url(srv) + "/healthz")
        assert h["preemptions"] == st["preemptions"]


def test_preempt_resume_bit_identical_sampled(params):
    """The sampler key is folded from the PREFILL stream id and rides
    the spill snapshot, so the resumed sid does not matter — but the
    victim must prefill as the same sid in both stacks (first
    submission on a fresh stack, both here and in the baseline)."""
    with _stack(params, settings=SAMPLED) as (srv, _):
        base, _raw = _post_sse(srv, VICTIM)
    base_ids = _ids_of(base)
    assert len(base_ids) == VICTIM["max_tokens"]
    with _stack(params, settings=SAMPLED) as (srv, sched):
        ids, _res = _preempt_run(srv, VICTIM)
        assert sched.stats()["preemptions"] >= 1
        assert ids == base_ids


def test_preempt_resume_constrained_mid_grammar(params):
    body = dict(VICTIM, prompt="ab", max_tokens=20,
                response_format={"type": "regex",
                                 "pattern": "[a-d]{20}"})
    with _stack(params) as (srv, _):
        base, _raw = _post_sse(srv, body)
    base_ids = _ids_of(base)
    assert len(base_ids) == 20
    with _stack(params) as (srv, sched):
        ids, _res = _preempt_run(srv, body)
        assert sched.stats()["preemptions"] >= 1
        assert ids == base_ids
        tok = _FakeTok()
        assert all(c in "abcd" for c in tok.decode(ids))


# -- spill chaos matrix ------------------------------------------------------


@pytest.mark.parametrize("kind", ["victim_finish", "spill_full"])
def test_chaos_aborted_preemption_leaves_victim_intact(
        params, greedy_base, kind):
    """A preemption attempt that dies at the worst protocol point —
    the victim retires under the scheduler's feet, or the spill store
    reports full — must leave the victim stream bit-identical and the
    interactive request served (by a retried preemption or by simply
    waiting out the victim)."""
    with _stack(params) as (srv, sched):
        chaos = SpillChaos([SpillFault(kind, at=1)])
        sched.spill_chaos = chaos
        ids, res = _preempt_run(srv, VICTIM)
        assert ids == greedy_base
        assert len(res["token_ids"]) == INTERACTIVE["max_tokens"]
        assert chaos.events == [(f"{kind}@1", 1)]
        assert sched.stats()["spilled"] == 0


def test_chaos_resume_storm_bit_identical(params, greedy_base):
    """The storm forces every spilled victim back through the import
    path at once — while the engine is still saturated, so the resumes
    queue as deferred imports instead of landing — and the victim's
    stream must still come back byte-for-byte."""
    with _stack(params) as (srv, sched):
        chaos = SpillChaos([SpillFault("resume_storm", at=1)])
        sched.spill_chaos = chaos
        ids, res = _preempt_run(srv, VICTIM)
        assert ids == greedy_base
        assert len(res["token_ids"]) == INTERACTIVE["max_tokens"]
        assert sched.stats()["preemptions"] >= 1
        assert ("resume_storm@1", 1) in chaos.events
        assert sched.stats()["spilled"] == 0


# -- admission deferral under spill pressure (satellite) ---------------------


def test_admit_defer_counts_once_per_deferred_admission(params):
    """kvpool.admit_defers is per deferred ADMISSION, not per deferring
    tick: a spilled stream resuming into a full pool defers across many
    steps but counts exactly once, and the eventual landing does not
    recount."""
    donor = _egen(params)
    donor.set_prompts([[1] * 40])
    _drive(donor, 0, 12)
    snap = donor.export_stream(0)  # the spill payload shape

    # 3 streams x 4 pages fill the 16-page pool: the 4-page resume must
    # wait for a retirement
    b = _egen(params, pool=16)
    b.set_prompts([[1] * 40, [2] * 40, [3] * 40])
    for sid in (0, 1, 2):
        _drive(b, sid, 12)
    d0 = b._pagepool._defer_ctr.value
    b.import_begin(snap)
    b.import_attach(peek_xfer_id(snap), 7)
    for _ in range(6):
        b.step()
    assert b.imports_pending() == 1
    assert b._pagepool._defer_ctr.value == d0 + 1, \
        "deferral must count once per admission, not once per tick"
    ref = _drive(donor, 0, 18)
    b.finish(2)  # pressure drops: 4 pages + a slot free up
    assert _drive(b, 7, 18) == ref  # resumed bit-identically
    assert b._pagepool._defer_ctr.value == d0 + 1, \
        "the landing recounted the deferral"


# -- per-tenant fairness -----------------------------------------------------


def test_over_budget_tenant_queues_behind(params):
    """A tenant that just burned a large token share queues behind an
    in-budget arrival of the SAME class that arrived later, and the
    bypass shows up on serve.tenant_throttled."""
    with _stack(params, spill_mb=0.0, fairness_factor=0.5) as (srv, _):
        # hog earns its share first (the accountant decays over ~10s,
        # far longer than this test)
        _post(srv, {"prompt": "abcd", "max_tokens": 24,
                    "class": "batch", "tenant": "hog"})
        t0 = THROTTLED.value
        first_token = threading.Event()
        occ = threading.Thread(target=_post_sse, args=(
            srv, {"prompt": "dcba", "max_tokens": 40,
                  "class": "interactive"}),
            kwargs={"on_event": lambda ev: first_token.set()})
        occ.start()
        assert first_token.wait(60)
        order, lock = [], threading.Lock()

        def client(name, body):
            _post(srv, body)
            with lock:
                order.append(name)

        th = threading.Thread(target=client, args=(
            "hog", {"prompt": "bb", "max_tokens": 2, "class": "batch",
                    "tenant": "hog"}))
        tf = threading.Thread(target=client, args=(
            "fair", {"prompt": "cc", "max_tokens": 2, "class": "batch",
                     "tenant": "fair"}))
        th.start()
        assert _wait_queued(srv, 1)  # hog queues first; fair must jump it
        tf.start()
        assert _wait_queued(srv, 2)
        for t in (th, tf, occ):
            t.join(timeout=120)
            assert not t.is_alive()
        assert order[0] == "fair", f"over-budget tenant served first: " \
                                   f"{order}"
        assert THROTTLED.value > t0


# -- /v1/batch bulk endpoint -------------------------------------------------


def test_batch_endpoint_resumable_roundtrip(server):
    body = {"prompts": ["abcd", "bcde", "cdef"], "max_tokens": 4,
            "id": "batch-t1"}
    out = _post(server, body, path="/v1/batch")
    assert out["id"] == "batch-t1" and out["object"] == "batch"
    assert out["status"] == "done" and out["n"] == 3 and out["done"] == 3
    for p, r in zip(body["prompts"], out["results"]):
        assert r["finish_reason"] == "length"
        solo = _post(server, {"prompt": p, "max_tokens": 4,
                              "class": "batch"})
        assert r["token_ids"] == solo["token_ids"]
        assert r["text"] == solo["text"]
    # resumable by id after a disconnect...
    again = _get(_url(server) + "/v1/batch/batch-t1")
    assert again["results"] == out["results"]
    # ...and via an idempotent re-POST (answered from the registry)
    re_post = _post(server, body, path="/v1/batch")
    assert re_post["results"] == out["results"]
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(_url(server) + "/v1/batch/no-such-batch")
    assert e.value.code == 404


def test_batch_endpoint_validation(server):
    for bad in ({}, {"prompts": []}, {"prompts": "abcd"},
                {"prompts": ["ab"], "id": ""}):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(server, bad, path="/v1/batch")
        assert e.value.code == 400
    # a bad prompt becomes a result row, not a failed batch
    out = _post(server, {"prompts": ["abcd", ["not", "ints"]],
                         "max_tokens": 2}, path="/v1/batch")
    assert out["status"] == "done"
    assert out["results"][0]["finish_reason"] == "length"
    assert out["results"][1]["status"] == 400


def test_batch_endpoint_self_throttles_past_queue_depth(params):
    """More prompts than slots + queue: the endpoint must drain and
    retry instead of surfacing QueueFull."""
    with _stack(params, max_concurrent=1, queue_depth=2) as (srv, _):
        out = _post(srv, {"prompts": [f"a{chr(98 + i)}" for i in range(8)],
                          "max_tokens": 2}, path="/v1/batch")
        assert out["status"] == "done" and out["done"] == 8
        assert all(r["finish_reason"] == "length" for r in out["results"])


# -- gateway: classed requests ride through untouched ------------------------


def test_gateway_vs_direct_classed_parity(params):
    """The gateway forwards class/tenant bodies byte-for-byte: an SSE
    stream through the gateway is token-line-identical to a direct
    connection, for both classes, and batch-class unary responses
    match. pick_batch itself routes to the least-loaded replica."""
    stacks = []
    for _ in range(2):
        gen = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                             settings=SamplerSettings(**GREEDY),
                             kv_layout="paged", kv_page_size=16)
        sched = Scheduler(gen, queue_depth=8, request_timeout_s=120)
        sched.start(max_concurrent=2)
        srv = start_api_server(sched)
        stacks.append((srv, sched))
    backends = [Backend(f"slo{i}", f"127.0.0.1:{srv.port}")
                for i, (srv, _) in enumerate(stacks)]
    mon = HealthMonitor(backends, probe_interval=0.2, up_after=1)
    mon.start(initial_probe=True)
    gw = start_gateway(mon, make_policy("prefix", prefix_block=8),
                       connect_timeout=1.0, read_timeout=60.0)
    try:
        direct = f"http://127.0.0.1:{stacks[0][0].port}"
        gw_url = f"http://127.0.0.1:{gw.port}"
        for cls in ("interactive", "batch"):
            body = {"prompt": "abcd", "max_tokens": 6, "class": cls,
                    "tenant": "acme"}
            _d_ev, d_raw = _post_sse(direct, body)
            _g_ev, g_raw = _post_sse(gw_url, body)
            assert [r for r in g_raw if b'"token"' in r] \
                == [r for r in d_raw if b'"token"' in r], \
                f"gateway reframed a {cls} stream"
            d_out = _post(direct, body)
            g_out = _post(gw_url, body)
            assert g_out["token_ids"] == d_out["token_ids"]
        # unknown class 400s identically through the gateway
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(gw_url, {"prompt": "ab", "max_tokens": 2,
                           "class": "premium"})
        assert e.value.code == 400
    finally:
        gw.close()
        mon.stop()
        for srv, sched in stacks:
            srv.close()
            sched.close()
