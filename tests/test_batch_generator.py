"""Multi-stream serving: concurrent batch decode must reproduce each stream's
single-run output exactly (the per-row positions + per-stream keys contract).

The reference is single-request only (SURVEY.md §0); these tests hold the
TPU-native batch plane to the strongest bar available: stream output depends
only on (seed, stream_id, prompt) — invariant to batch composition, dp
layout, block size, and the other streams in the batch.
"""

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.runtime.batch_generator import BatchGenerator as BG

CFG = tiny(max_seq_len=64)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)
PROMPTS = [[5, 9, 2, 11], [3, 1, 4, 1, 5, 9], [7, 7, 2]]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(5))


def _single_stream(params, prompt, n, settings):
    g = LlamaGenerator(CFG, params, settings=settings)
    g.set_prompt(prompt)
    out = []
    for i in range(n):
        t = g.next_token(i)
        out.append(t.id)
        if t.is_end_of_stream:
            break
    return out


def _batch_run(params, prompts, n, settings, stream_ids=None, **kw):
    g = BatchGenerator(CFG, params, settings=settings, **kw)
    g.set_prompts(prompts, stream_ids=stream_ids)
    return g.generate(n)


@pytest.mark.parametrize("dp,stages,tp", [(1, 1, 1), (2, 1, 1), (2, 2, 2),
                                          (4, 2, 1)])
def test_greedy_batch_matches_single_runs(params, dp, stages, tp):
    """Different-length prompts decode concurrently; every stream's greedy
    tokens equal its standalone single-stream run (positions are per-row, so
    right-padding another stream's prompt cannot shift RoPE/mask geometry)."""
    settings = SamplerSettings(**GREEDY)
    got = _batch_run(params, PROMPTS, 8, settings, dp=dp, num_stages=stages,
                     tp=tp)
    for prompt, stream in zip(PROMPTS, got):
        assert stream == _single_stream(params, prompt, 8, settings)


def test_greedy_block_decode_matches(params):
    settings = SamplerSettings(**GREEDY)
    want = [_single_stream(params, p, 9, settings) for p in PROMPTS]
    got = _batch_run(params, PROMPTS, 9, settings, dp=2, block_size=4)
    assert got == want


def test_sampled_stream_invariant_to_batch_composition(params):
    """A sampled stream is keyed by (seed, stream_id): running it alone,
    with different companions, or on a different dp layout yields the same
    tokens."""
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=11)
    full = _batch_run(params, PROMPTS, 8, settings, dp=1)
    # same streams, different layout
    assert _batch_run(params, PROMPTS, 8, settings, dp=2) == full
    # stream 1 alone, pinned to its stream_id
    alone = _batch_run(params, [PROMPTS[1]], 8, settings, stream_ids=[1], dp=1)
    assert alone == [full[1]]
    # different companion set, same ids for the survivors
    pair = _batch_run(params, [PROMPTS[0], PROMPTS[2]], 8, settings,
                      stream_ids=[0, 2], dp=2)
    assert pair == [full[0], full[2]]


def test_sampled_block_size_invariant(params):
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=11)
    assert (
        _batch_run(params, PROMPTS, 8, settings, dp=1, block_size=4)
        == _batch_run(params, PROMPTS, 8, settings, dp=1)
    )


def test_eos_stops_stream_independently(params):
    """A stream hitting EOS goes quiet while others continue."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=1)
    # find the greedy continuation of prompt 0 and use its 3rd token as EOS
    ref = _single_stream(params, PROMPTS[0], 6, settings)
    eos_cfg = tiny(max_seq_len=64, eos_token_id=ref[2])
    g = BG(eos_cfg, params, settings=settings, dp=1)
    g.set_prompts([PROMPTS[0], PROMPTS[1]])
    outs = [g.step() for _ in range(6)]
    # stream 0 emitted exactly 3 tokens, the last flagged EOS
    s0 = [row[0] for row in outs if row[0] is not None]
    assert len(s0) == 3 and s0[-1].is_end_of_stream
    # stream 1 kept decoding its own (unchanged) stream
    s1 = [row[1].id for row in outs if row[1] is not None]
    assert s1 == _single_stream(params, PROMPTS[1], 6, settings)[:len(s1)]
    assert len(s1) == 6


def test_short_stream_survives_long_stream_window_exhaustion(params):
    """A long stream hitting max_seq goes quiet (window_full => done); the
    short stream keeps decoding into its own remaining KV room, with tokens
    identical to its standalone run (code-review r2 regression)."""
    settings = SamplerSettings(**GREEDY)
    cfg = tiny(max_seq_len=32)
    long_prompt = list(range(2, 28))  # 26 tokens -> only 6 slots left
    short_prompt = [5, 9, 2]
    for block_size in (1, 4):
        g = BG(cfg, params, settings=settings, dp=1, block_size=block_size)
        g.set_prompts([long_prompt, short_prompt])
        outs = g.generate(20)
        assert len(outs[0]) == 32 - len(long_prompt)  # filled its window
        assert len(outs[1]) == 20  # unbothered
        solo = BG(cfg, params, settings=settings, dp=1, block_size=block_size)
        solo.set_prompts([short_prompt], stream_ids=[1])
        assert solo.generate(20)[0] == outs[1]


def test_batch_padding_to_dp_multiple(params):
    """3 prompts on dp=2 pad to 4 rows with an inactive dummy; outputs still
    match, dummy never surfaces."""
    settings = SamplerSettings(**GREEDY)
    got = _batch_run(params, PROMPTS, 6, settings, dp=2)
    assert len(got) == 3
    for prompt, stream in zip(PROMPTS, got):
        assert stream == _single_stream(params, prompt, 6, settings)
