"""Multi-stream serving: concurrent batch decode must reproduce each stream's
single-run output exactly (the per-row positions + per-stream keys contract).

The reference is single-request only (SURVEY.md §0); these tests hold the
TPU-native batch plane to the strongest bar available: stream output depends
only on (seed, stream_id, prompt) — invariant to batch composition, dp
layout, block size, and the other streams in the batch.
"""

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.runtime.batch_generator import BatchGenerator as BG

CFG = tiny(max_seq_len=64)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)
PROMPTS = [[5, 9, 2, 11], [3, 1, 4, 1, 5, 9], [7, 7, 2]]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(5))


def _single_stream(params, prompt, n, settings):
    g = LlamaGenerator(CFG, params, settings=settings)
    g.set_prompt(prompt)
    out = []
    for i in range(n):
        t = g.next_token(i)
        out.append(t.id)
        if t.is_end_of_stream:
            break
    return out


def _batch_run(params, prompts, n, settings, stream_ids=None, **kw):
    g = BatchGenerator(CFG, params, settings=settings, **kw)
    g.set_prompts(prompts, stream_ids=stream_ids)
    return g.generate(n)


@pytest.mark.parametrize("dp,stages,tp", [(1, 1, 1), (2, 1, 1), (2, 2, 2),
                                          (4, 2, 1)])
def test_greedy_batch_matches_single_runs(params, dp, stages, tp):
    """Different-length prompts decode concurrently; every stream's greedy
    tokens equal its standalone single-stream run (positions are per-row, so
    right-padding another stream's prompt cannot shift RoPE/mask geometry)."""
    settings = SamplerSettings(**GREEDY)
    got = _batch_run(params, PROMPTS, 8, settings, dp=dp, num_stages=stages,
                     tp=tp)
    for prompt, stream in zip(PROMPTS, got):
        assert stream == _single_stream(params, prompt, 8, settings)


def test_greedy_block_decode_matches(params):
    settings = SamplerSettings(**GREEDY)
    want = [_single_stream(params, p, 9, settings) for p in PROMPTS]
    got = _batch_run(params, PROMPTS, 9, settings, dp=2, block_size=4)
    assert got == want


def test_sampled_stream_invariant_to_batch_composition(params):
    """A sampled stream is keyed by (seed, stream_id): running it alone,
    with different companions, or on a different dp layout yields the same
    tokens."""
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=11)
    full = _batch_run(params, PROMPTS, 8, settings, dp=1)
    # same streams, different layout
    assert _batch_run(params, PROMPTS, 8, settings, dp=2) == full
    # stream 1 alone, pinned to its stream_id
    alone = _batch_run(params, [PROMPTS[1]], 8, settings, stream_ids=[1], dp=1)
    assert alone == [full[1]]
    # different companion set, same ids for the survivors
    pair = _batch_run(params, [PROMPTS[0], PROMPTS[2]], 8, settings,
                      stream_ids=[0, 2], dp=2)
    assert pair == [full[0], full[2]]


def test_sampled_block_size_invariant(params):
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=11)
    assert (
        _batch_run(params, PROMPTS, 8, settings, dp=1, block_size=4)
        == _batch_run(params, PROMPTS, 8, settings, dp=1)
    )


def test_eos_stops_stream_independently(params):
    """A stream hitting EOS goes quiet while others continue."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=1)
    # find the greedy continuation of prompt 0 and use its 3rd token as EOS
    ref = _single_stream(params, PROMPTS[0], 6, settings)
    eos_cfg = tiny(max_seq_len=64, eos_token_id=ref[2])
    g = BG(eos_cfg, params, settings=settings, dp=1)
    g.set_prompts([PROMPTS[0], PROMPTS[1]])
    outs = [g.step() for _ in range(6)]
    # stream 0 emitted exactly 3 tokens, the last flagged EOS
    s0 = [row[0] for row in outs if row[0] is not None]
    assert len(s0) == 3 and s0[-1].is_end_of_stream
    # stream 1 kept decoding its own (unchanged) stream
    s1 = [row[1].id for row in outs if row[1] is not None]
    assert s1 == _single_stream(params, PROMPTS[1], 6, settings)[:len(s1)]
    assert len(s1) == 6


def test_short_stream_survives_long_stream_window_exhaustion(params):
    """A long stream hitting max_seq goes quiet (window_full => done); the
    short stream keeps decoding into its own remaining KV room, with tokens
    identical to its standalone run (code-review r2 regression)."""
    settings = SamplerSettings(**GREEDY)
    cfg = tiny(max_seq_len=32)
    long_prompt = list(range(2, 28))  # 26 tokens -> only 6 slots left
    short_prompt = [5, 9, 2]
    for block_size in (1, 4):
        g = BG(cfg, params, settings=settings, dp=1, block_size=block_size)
        g.set_prompts([long_prompt, short_prompt])
        outs = g.generate(20)
        assert len(outs[0]) == 32 - len(long_prompt)  # filled its window
        assert len(outs[1]) == 20  # unbothered
        solo = BG(cfg, params, settings=settings, dp=1, block_size=block_size)
        solo.set_prompts([short_prompt], stream_ids=[1])
        assert solo.generate(20)[0] == outs[1]


def test_window_edge_stream_keeps_batch_on_block_dispatch(params):
    """Fused-block eligibility is per-row: one stream 2 tokens from its
    window must NOT force the whole batch into single-step dispatches (r2
    VERDICT weak #7). Dispatch count stays ~N/block_size, the edge stream
    fills its window with exactly its solo tokens, and mid-window streams
    are bit-identical to their solo runs."""
    settings = SamplerSettings(**GREEDY)
    cfg = tiny(max_seq_len=32)
    block = 4
    edge_prompt = list(range(2, 28))  # 26 tokens -> 6 slots left (< 2 blocks)
    mids = [[5, 9, 2], [3, 1, 4], [7, 7, 2], [2, 8, 1]]
    g = BG(cfg, params, settings=settings, dp=1, block_size=block)
    g.set_prompts([edge_prompt] + mids)
    calls = {"block": 0, "single": 0}
    real_block, real_single = g._decode_block, g._decode_single

    def count_block(*a, **k):
        calls["block"] += 1
        return real_block(*a, **k)

    def count_single(*a, **k):
        calls["single"] += 1
        return real_single(*a, **k)

    g._decode_block, g._decode_single = count_block, count_single
    n = 20
    outs = g.generate(n)
    assert calls["single"] == 0
    assert calls["block"] == -(-(n - 1) // block)  # first token from prefill
    assert len(outs[0]) == 32 - len(edge_prompt)  # edge filled its window
    solo_edge = _single_stream(params, edge_prompt, n, settings)
    # solo run raises window exhaustion at the same boundary; compare prefix
    assert outs[0] == solo_edge[: len(outs[0])]
    for prompt, got in zip(mids, outs[1:]):
        assert got == _single_stream(params, prompt, n, settings)


@pytest.mark.parametrize("block_size", [1, 4])
def test_admit_refills_finished_slot(params, block_size):
    """Continuous-batching-lite: when a stream finishes, admit() splices a
    new prompt into its slot mid-run. The admitted stream reproduces its
    solo run exactly (per-row positions AND per-row token indices), and the
    untouched neighbor stream is bit-identical to its own solo run."""
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=11)
    cfg = tiny(max_seq_len=32)
    long_prompt = list(range(2, 28))  # 26 tokens -> done after 6
    g = BG(cfg, params, settings=settings, dp=1, block_size=block_size)
    g.set_prompts([long_prompt, PROMPTS[1]], stream_ids=[0, 1])
    for _ in range(6):
        g.step()
    assert g.streams[0].done and not g.streams[1].done

    slot, first = g.admit(PROMPTS[2], stream_id=7)
    assert slot == 0
    collected = [first.id]
    for _ in range(7):
        row = g.step()
        if row[0] is not None:
            collected.append(row[0].id)

    solo = BG(cfg, params, settings=settings, dp=1, block_size=block_size)
    solo.set_prompts([PROMPTS[2]], stream_ids=[7])
    assert collected == solo.generate(24)[0][: len(collected)]

    s1 = g.streams[1].generated
    solo1 = BG(cfg, params, settings=settings, dp=1, block_size=block_size)
    solo1.set_prompts([PROMPTS[1]], stream_ids=[1])
    assert s1 == solo1.generate(24)[0][: len(s1)]


def test_admit_into_dummy_slot_before_first_step(params):
    """admit() may claim a dp-padding dummy slot before the first step();
    the admitted stream's first token is returned by admit() once, not
    re-emitted by the first step() (code-review r2 regression)."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=2)
    g.set_prompts(PROMPTS)  # 3 prompts -> 4 rows, slot 3 is a dummy
    slot, first = g.admit(PROMPTS[0], stream_id=9)
    assert slot == 3
    rows = [g.step() for _ in range(8)]
    got = [first.id] + [r[slot].id for r in rows if r[slot] is not None]
    want = _single_stream(params, PROMPTS[0], len(got), settings)
    assert got == want
    # exactly one copy of the first token
    assert g.streams[slot].generated == got


def test_admit_flush_preserves_streamed_tokens(params):
    """Tokens buffered by block decode at admission time still reach the
    streaming step() consumer (queued rows), not just the generated lists."""
    settings = SamplerSettings(**GREEDY)
    cfg = tiny(max_seq_len=32)
    long_prompt = list(range(2, 28))
    g = BG(cfg, params, settings=settings, dp=1, block_size=4)
    g.set_prompts([long_prompt, PROMPTS[1]], stream_ids=[0, 1])
    received = {0: [], 1: [], 7: []}

    def collect(row, admitted_slot=None):
        for i, t in enumerate(row):
            if t is not None:
                sid = g.streams[i].stream_id
                received[sid].append(t.id)

    for _ in range(6):
        collect(g.step())
    slot, first = g.admit(PROMPTS[2], stream_id=7)
    received[7].append(first.id)
    for _ in range(8):
        collect(g.step())
    # every recorded token reached the streaming consumer, in order
    for s in g.streams:
        assert received[s.stream_id] == s.generated


def test_admit_requires_free_slot(params):
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=1)
    g.set_prompts(PROMPTS)
    with pytest.raises(RuntimeError, match="no free slot"):
        g.admit([1, 2, 3], stream_id=9)


def test_enqueue_interleaves_admission_with_decode(params):
    """Real continuous batching: a queued arrival's prefill advances one
    chunk per step ALONGSIDE the decode dispatches (the running batch never
    stalls behind a full prompt pass), as one replicated row (no dp
    discarded copies). The admitted stream and the untouched neighbor are
    both bit-identical to their solo runs."""
    settings = SamplerSettings(**GREEDY)
    new_prompt = [2, 8, 1, 7, 6, 5, 4, 3]  # 8 tokens -> 2 chunks of 4
    g = BG(CFG, params, settings=settings, dp=1, admit_chunk=4)
    g.set_prompts(PROMPTS[:2])
    rows = [g.step(), g.step()]  # first token + one decode
    g.streams[0].done = True  # slot 0 frees up

    decode_calls = {"n": 0}
    real_single = g._decode_single

    def count_single(*a, **k):
        decode_calls["n"] += 1
        return real_single(*a, **k)

    g._decode_single = count_single
    admit_calls = {"n": 0}
    real_admit = g._admit_prefill  # property: compiles the program

    def count_admit(*a, **k):
        admit_calls["n"] += 1
        return real_admit(*a, **k)

    g._BatchGenerator__admit_prefill = count_admit

    g.enqueue(new_prompt, stream_id=7)
    assert g.pending_admissions() == 1
    rows.append(g.step())  # chunk 1 of the admission + a decode dispatch
    assert admit_calls["n"] == 1 and decode_calls["n"] == 1
    assert rows[-1][1] is not None  # the neighbor stream kept decoding
    rows.append(g.step())  # chunk 2 (final): emits the first token row
    assert admit_calls["n"] == 2 and g.pending_admissions() == 0
    assert rows[-1][0] is not None and rows[-1][1] is None
    for _ in range(4):
        rows.append(g.step())

    admitted = [r[0].id for r in rows[3:] if r[0] is not None]
    solo = BG(CFG, params, settings=settings, dp=1)
    solo.set_prompts([new_prompt], stream_ids=[7])
    assert admitted == solo.generate(len(admitted))[0][: len(admitted)]

    neighbor = [r[1].id for r in rows if r[1] is not None]
    assert neighbor == _single_stream(params, PROMPTS[1], len(neighbor),
                                      settings)


def test_enqueue_waits_for_free_slot_and_drains_fifo(params):
    """Arrivals queue FIFO; admission starts only once a slot frees."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=1)
    g.set_prompts(PROMPTS[:2])
    g.step()
    g.enqueue([2, 8, 1], stream_id=5)
    g.enqueue([4, 4, 4], stream_id=6)
    g.step()
    assert g.pending_admissions() == 2  # no free slot yet
    g.streams[0].done = True
    g.step()  # whole bucketed prompt in one dispatch (admit_chunk=None)
    assert g.pending_admissions() == 1  # first arrival admitted
    sids = sorted(s.stream_id for s in g.streams)
    assert 5 in sids and 6 not in sids
    g.streams[1].done = True
    g.step()
    assert g.pending_admissions() == 0
    assert sorted(s.stream_id for s in g.streams) == [5, 6]


def test_finish_retires_stream_and_frees_slot(params):
    """The public retirement API (the serving plane's slot free): finish()
    stops the stream's emission, makes its slot admissible to the next
    arrival, and reports retirement races honestly (False on an unknown or
    already-done id — normal for a server, not an error)."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=1)
    g.set_prompts(PROMPTS[:2])
    g.step()
    assert g.finish(0) is True
    assert g.streams[0].done
    assert g.finish(0) is False  # already retired
    assert g.finish(42) is False  # never admitted
    row = g.step()
    assert row[0] is None and row[1] is not None  # retired slot is silent
    g.enqueue([2, 8, 1], stream_id=5)
    g.step()
    assert g.pending_admissions() == 0  # admitted into the freed slot
    assert g.streams[0].stream_id == 5
    # the neighbor stream was never perturbed
    neighbor = [r[1].id for r in [row] if r[1] is not None]
    assert neighbor == _single_stream(params, PROMPTS[1], 2, settings)[1:2]


def test_finish_cancels_queued_and_staging_arrivals(params):
    """finish() covers the arrival's WHOLE lifecycle: an id still waiting
    in the FIFO, or mid-admission in the staging cache, is dropped before
    it can splice in — a server cancelling a request whose prefill never
    completed must not leak an ownerless stream into a slot."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=1, admit_chunk=4)
    g.set_prompts(PROMPTS[:2])
    g.step()
    # queued, never started: no slot free, the arrival sits in the FIFO
    g.enqueue([2, 8, 1, 7], stream_id=9)
    assert g.pending_admissions() == 1
    assert g.finish(9) is True
    assert g.pending_admissions() == 0
    # mid-staging: free a slot, let one 4-token chunk of an 8-token
    # arrival dispatch, then retire it before the final chunk
    g.finish(0)
    g.enqueue([2, 8, 1, 7, 6, 5, 4, 3], stream_id=10)
    g.step()  # chunk 1 of 2 into the staging cache
    assert g.pending_admissions() == 1  # in flight
    assert g.finish(10) is True
    assert g.pending_admissions() == 0
    for _ in range(3):
        g.step()
    assert all(s.stream_id != 10 for s in g.streams)  # never spliced
    # the freed slot still serves the next arrival
    g.enqueue([4, 4, 4], stream_id=11)
    g.step()
    assert any(s.stream_id == 11 for s in g.streams)


def test_admit_chunk_must_divide_max_seq(params):
    """A chunk that doesn't divide the window is rejected at construction:
    a near-window prompt would round up PAST max_seq and the final chunk's
    clamped KV write would silently corrupt committed slots (repro'd:
    admit_chunk=6/max_seq=32 with a 31-token prompt flipped the admitted
    stream's first token)."""
    cfg = tiny(max_seq_len=32)
    with pytest.raises(ValueError, match="positive divisor of"):
        BG(cfg, params, settings=SamplerSettings(**GREEDY), dp=1,
           admit_chunk=6)
    with pytest.raises(ValueError, match="positive divisor of"):
        BG(cfg, params, settings=SamplerSettings(**GREEDY), dp=1,
           admit_chunk=0)
    with pytest.raises(ValueError, match="positive divisor of"):
        BG(cfg, params, settings=SamplerSettings(**GREEDY), dp=1,
           admit_chunk=-4)
    # dividing chunk + near-window prompt: exact admission
    settings = SamplerSettings(**GREEDY)
    near = list(range(2, 2 + 29))  # 29 tokens into a 32 window
    g = BG(cfg, params, settings=settings, dp=1, admit_chunk=8)
    g.set_prompts([[5, 9, 2]])
    g.step()
    g.streams[0].done = True
    g.enqueue(near, stream_id=3)
    rows = [g.step() for _ in range(6)]
    got = [r[0].id for r in rows if r[0] is not None]
    solo = BG(cfg, params, settings=settings, dp=1)
    solo.set_prompts([near], stream_ids=[3])
    assert got == solo.generate(len(got))[0][: len(got)]


def test_admit_with_queued_arrivals_exceeding_slots_raises(params):
    """admit() with more arrivals than free slots must raise, not hang:
    the drain loop detects a stuck queue head (no staging, no free slot)
    and removes the caller's arrival (regression: infinite busy loop)."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=1)
    g.set_prompts(PROMPTS[:2])
    g.step()
    g.streams[0].done = True  # exactly one free slot
    g.enqueue([2, 8, 1], stream_id=5)  # will take the only slot
    with pytest.raises(RuntimeError, match="no free slot"):
        g.admit([4, 4, 4], stream_id=6)
    # the queued arrival was admitted on the way; ours was removed
    assert g.pending_admissions() == 0
    assert 5 in [s.stream_id for s in g.streams]
    assert 6 not in [s.stream_id for s in g.streams]


def test_enqueue_with_dp_sharded_batch(params):
    """The admission row is replicated over dp (batch_replicated staging
    cache), so continuous admission works on a dp-sharded batch too."""
    settings = SamplerSettings(**GREEDY)
    new_prompt = [2, 8, 1]
    g = BG(CFG, params, settings=settings, dp=2, admit_chunk=4)
    g.set_prompts(PROMPTS[:2])
    g.step()
    g.streams[0].done = True
    g.enqueue(new_prompt, stream_id=11)
    rows = [g.step() for _ in range(6)]
    admitted = [r[0].id for r in rows if r[0] is not None]
    assert admitted
    solo = BG(CFG, params, settings=settings, dp=2)
    solo.set_prompts([new_prompt], stream_ids=[11])
    assert admitted == solo.generate(len(admitted))[0][: len(admitted)]


def test_shared_prefix_prefilled_once_bit_identical(params):
    """Prompts sharing a long common prefix (the system-prompt case):
    the prefix is prefilled ONCE as a single replicated row and broadcast,
    remainders prefill at the offset — every stream's tokens are
    bit-identical to the unshared path, and the batched prefill sees only
    the remainder lengths."""
    settings = SamplerSettings(**GREEDY)
    sys_prompt = [7, 3, 9, 1, 4, 8, 2, 6] * 2  # 16 shared tokens
    prompts = [sys_prompt + tail
               for tail in ([5, 9, 2], [3, 1, 4, 1], [8, 8])]

    def run(share_min):
        g = BG(CFG, params, settings=settings, dp=1, block_size=4,
               prefix_share_min=share_min)
        calls = {}
        orig = g._prefill

        def spy(p, toks, cache, last, *rest):
            calls["prefill_T"] = toks.shape[1]
            return orig(p, toks, cache, last, *rest)

        g._prefill = spy
        g.set_prompts(prompts)
        return g.generate(8), calls, g

    unshared, calls_u, _ = run(share_min=0)
    shared, calls_s, g = run(share_min=8)
    assert shared == unshared
    # unshared path buckets the FULL prompts; shared path never calls the
    # plain prefill at all (prefix row + offset remainder program)
    assert calls_u["prefill_T"] >= 19
    assert "prefill_T" not in calls_s
    assert g.stats()["admit_dispatches"] >= 1  # the prefix row dispatch


def test_shared_prefix_skips_when_prefix_short_or_absent(params):
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=1, prefix_share_min=32)
    g.set_prompts([[5, 9, 2], [5, 9, 3]])  # 2-token prefix < threshold
    out = g.generate(6)
    for prompt, got in zip([[5, 9, 2], [5, 9, 3]], out):
        assert got == _single_stream(params, prompt, 6, settings)


def test_arrival_reuses_cached_prefix_row(params):
    """An enqueued arrival that opens with the batch's shared prefix
    starts from a copy of the cached prefix KV row and prefills only its
    remainder — fewer admission dispatches, tokens bit-identical to the
    from-scratch admission."""
    settings = SamplerSettings(**GREEDY)
    prefix = [(i * 7) % 100 + 2 for i in range(16)]
    prompts = [prefix + [5, 9, 2], prefix + [3, 1, 4]]
    new_prompt = prefix + [8, 8, 4]

    def run(share_min):
        g = BG(CFG, params, settings=settings, dp=1, admit_chunk=8,
               prefix_share_min=share_min)
        g.set_prompts(prompts)
        g.step()
        g.streams[0].done = True
        d0 = g.stats()["admit_dispatches"]
        g.enqueue(list(new_prompt), stream_id=9)
        rows = [g.step() for _ in range(8)]
        toks = [r[0].id for r in rows if r[0] is not None]
        return toks, g.stats()["admit_dispatches"] - d0

    toks_scratch, n_scratch = run(share_min=0)
    toks_reuse, n_reuse = run(share_min=8)
    solo = BG(CFG, params, settings=settings, dp=1)
    solo.set_prompts([list(new_prompt)], stream_ids=[9])
    want = solo.generate(12)[0]
    # same stream either way; the reuse run admits earlier so the same
    # step budget yields MORE of it
    assert toks_scratch == want[: len(toks_scratch)]
    assert toks_reuse == want[: len(toks_reuse)]
    assert len(toks_reuse) >= len(toks_scratch)
    # scratch prefills ceil(19/8)=3 chunks; reuse only the 3-token
    # remainder (1 chunk)
    assert n_scratch == 3 and n_reuse == 1
    # non-matching arrival falls back to from-scratch admission
    g = BG(CFG, params, settings=settings, dp=1, admit_chunk=8,
           prefix_share_min=8)
    g.set_prompts(prompts)
    g.step()
    g.streams[0].done = True
    g.enqueue([4, 4, 4, 4], stream_id=7)
    rows = [g.step() for _ in range(6)]
    toks = [r[0].id for r in rows if r[0] is not None]
    solo = BG(CFG, params, settings=settings, dp=1)
    solo.set_prompts([[4, 4, 4, 4]], stream_ids=[7])
    assert toks == solo.generate(len(toks))[0][: len(toks)]


def test_shared_prefix_near_window_does_not_overrun(params):
    """The remainder bucket is capped at the room above the prefix: a long
    shared prefix with near-window prompts must not clamp-overwrite
    committed prefix KV (regression: t_pad bucketed past max_seq - lcp)."""
    settings = SamplerSettings(**GREEDY)
    cfg = tiny(max_seq_len=64)
    prefix = [(i * 7) % 100 + 2 for i in range(40)]
    prompts = [prefix + [5, 9, 2] * 7 + [1, 2],   # 63 tokens total
               prefix + [3, 1, 4]]
    g = BG(cfg, params, settings=settings, dp=1, prefix_share_min=16)
    g.set_prompts(prompts)
    out = g.generate(4)
    for prompt, got in zip(prompts, out):
        solo = BG(cfg, params, settings=settings, dp=1, prefix_share_min=0)
        solo.set_prompts([prompt], stream_ids=[prompts.index(prompt)])
        assert got == solo.generate(4)[0][: len(got)]


def test_shared_prefix_with_identical_prompts(params):
    """All-identical prompts (the dummy-padding shape): lcp caps one short
    of the prompt so every row keeps a remainder token."""
    settings = SamplerSettings(**GREEDY)
    p = [7, 3, 9, 1, 4, 8, 2, 6, 5, 9, 2, 4]
    g = BG(CFG, params, settings=settings, dp=2, prefix_share_min=4)
    g.set_prompts([list(p), list(p), list(p)])  # pads to 4 with a dummy
    out = g.generate(6)
    want = _single_stream(params, p, 6, settings)
    for got in out:
        assert got == want


def test_serving_stats_track_dispatches_and_tokens(params):
    """stats() reports the serving counters: emitted tokens, decode and
    admission dispatch counts, tokens-per-dispatch, and throughput."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, dp=1, block_size=4)
    g.set_prompts(PROMPTS[:2])
    for _ in range(9):
        g.step()
    g.streams[0].done = True
    g.enqueue([2, 8, 1], stream_id=5)
    for _ in range(4):
        g.step()
    st = g.stats()
    assert st["tokens_emitted"] > 0
    # 2 streams x 9 steps + admission-era rows, all accounted
    assert st["decode_dispatches"] >= 2  # ceil(8/4) blocks at minimum
    assert st["admit_dispatches"] == 1
    assert st["tokens_per_dispatch"] > 1  # block fusion amortizes
    assert st["busy_s"] > 0 and st["wall_s"] >= st["busy_s"] * 0.5
    assert st["aggregate_tok_s"] > 0
    assert st["streams_live"] >= 1 and st["pending_admissions"] == 0


def test_batch_padding_to_dp_multiple(params):
    """3 prompts on dp=2 pad to 4 rows with an inactive dummy; outputs still
    match, dummy never surfaces."""
    settings = SamplerSettings(**GREEDY)
    got = _batch_run(params, PROMPTS, 6, settings, dp=2)
    assert len(got) == 3
    for prompt, stream in zip(PROMPTS, got):
        assert stream == _single_stream(params, prompt, 6, settings)


def test_arrivals_with_distinct_prefixes_each_hit_their_own_row(params):
    """Generalized prefix store (r4): TWO different system prompts among
    arrivals each hit their OWN cached prefix row — not just the batch's
    single shared prefix. Every admitted arrival banks its block-aligned
    prefix, so the second arrival per system prompt prefills only its
    remainder (1 chunk instead of 3), bit-identical to a solo run."""
    settings = SamplerSettings(**GREEDY)
    sys_a = [(i * 7) % 100 + 2 for i in range(16)]
    sys_b = [(i * 11) % 100 + 3 for i in range(16)]
    arrivals = [
        (sys_a + [5, 9, 2], 10),   # scratch; banks sys_a
        (sys_b + [3, 1, 4], 11),   # scratch; banks sys_b
        (sys_a + [8, 8, 4], 12),   # hits the sys_a row
        (sys_b + [6, 2, 7], 13),   # hits the sys_b row
    ]

    g = BG(CFG, params, settings=settings, dp=1, admit_chunk=8,
           prefix_share_min=8, prefix_block=8)
    g.set_prompts([[4, 4, 4], [6, 6, 6]])
    g.step()
    admit_cost, emitted = {}, {}
    for prompt, sid in arrivals:
        for s in g.streams:
            s.done = True  # free a slot for the next arrival
        d0 = g.stats()["admit_dispatches"]
        g.enqueue(list(prompt), stream_id=sid)
        while g.pending_admissions():
            g.step()
        admit_cost[sid] = g.stats()["admit_dispatches"] - d0
        for _ in range(4):  # decode a few tokens before the slot is reused
            g.step()
        s = next(s for s in g.streams if s.active and s.stream_id == sid)
        emitted[sid] = list(s.generated)
    # first-of-a-prefix pays the full ceil(19/8)=3 chunks; repeats pay 1
    assert admit_cost[10] == 3 and admit_cost[11] == 3
    assert admit_cost[12] == 1 and admit_cost[13] == 1
    assert g.stats()["prefix_hits"] == 2
    assert g.stats()["prefix_entries"] == 2

    # bit-identity: each arrival's emitted tokens match a solo run of the
    # same (seed, stream_id, prompt) — hit or miss, any admission order
    for prompt, sid in arrivals:
        got = emitted[sid]
        assert got, sid
        solo = BG(CFG, params, settings=settings, dp=1)
        solo.set_prompts([list(prompt)], stream_ids=[sid])
        want = solo.generate(len(got))[0]
        assert got == want[: len(got)], (sid, got, want)


def test_prefix_store_lru_eviction(params):
    """The store is capped: a third distinct prefix evicts the least
    recently used row and later arrivals with the evicted prefix prefill
    from scratch again (correct, just unaided)."""
    settings = SamplerSettings(**GREEDY)
    mk = lambda seed: [(i * seed) % 90 + 2 for i in range(16)]
    g = BG(CFG, params, settings=settings, dp=1, admit_chunk=8,
           prefix_share_min=8, prefix_block=8, prefix_cache_entries=2)
    g.set_prompts([[4, 4, 4], [6, 6, 6]])
    g.step()
    sid = 20
    for seed in (7, 11, 13):  # third insert evicts the seed-7 row
        for s in g.streams:
            s.done = True
        g.enqueue(mk(seed) + [1, 2], stream_id=sid)
        sid += 1
        while g.pending_admissions():
            g.step()
    assert g.stats()["prefix_entries"] == 2
    for s in g.streams:
        s.done = True
    d0 = g.stats()["admit_dispatches"]
    g.enqueue(mk(7) + [9, 9], stream_id=sid)  # evicted: full prefill
    while g.pending_admissions():
        g.step()
    assert g.stats()["admit_dispatches"] - d0 == 3
    assert g.stats()["prefix_hits"] == 0


# -- batched serving speculation ----------------------------------------------

def test_serving_speculation_greedy_bit_identical(params):
    """spec_k > 0: every live stream's n-gram proposals verified in one
    per-row dispatch; greedy streams are bit-identical to plain serving
    decode with tokens-per-dispatch > 1 on repeating streams."""
    prompts = [[5, 9, 2, 5, 9, 2, 5, 9], [3, 1, 4, 1, 3, 1, 4, 1],
               [7, 7, 2, 8]]
    for penalty in (1.0, 1.1):
        settings = SamplerSettings(temperature=0.0, repeat_penalty=penalty)
        plain = BG(CFG, params, settings=settings)
        plain.set_prompts([list(p) for p in prompts])
        want = plain.generate(10)
        spec = BG(CFG, params, settings=settings, spec_k=4)
        spec.set_prompts([list(p) for p in prompts])
        got = spec.generate(10)
        assert got == want, penalty
        st = spec.stats()
        assert st["spec_dispatches"] >= 1
        assert st["tokens_per_dispatch"] > 1.0


def test_serving_speculation_sampled_invariant_to_composition(params):
    """temperature > 0 with spec_k: a stream's rejection-sampling draws
    derive only from (its key, its positions, its context), so the same
    (seed, stream_id, prompt) emits identical tokens in any batch
    composition."""
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=5)
    target = [5, 9, 2, 5, 9, 2, 5, 9]

    def run(other_prompts):
        g = BG(CFG, params, settings=settings, spec_k=4)
        g.set_prompts([list(target)] + [list(p) for p in other_prompts],
                      stream_ids=[42] + list(range(1, len(other_prompts) + 1)))
        return g.generate(8)[0]

    a = run([[3, 1, 4, 1]])
    b = run([[8, 8], [2, 6, 4], [9, 1, 1]])
    assert a == b
    assert all(0 <= t < CFG.vocab_size for t in a)


def test_serving_speculation_window_edge_falls_back(params):
    """A live stream too close to its window for K+1 fed slots forces the
    plain decode path — correct output, no overrun."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    long_prompt = [(i * 5) % 90 + 2 for i in range(56)]  # 56 of 64 window
    plain = BG(CFG, params, settings=settings)
    plain.set_prompts([list(long_prompt)])
    want = plain.generate(7)
    spec = BG(CFG, params, settings=settings, spec_k=6)
    spec.set_prompts([list(long_prompt)])
    got = spec.generate(7)
    assert got == want


_SPEC_ADMIT_STREAMS = ((0, [5, 9, 2, 5, 9, 2]), (9, [8, 2, 8, 2, 8, 2]))


def _drive_spec_admission(params, settings, plan=None):
    """Shared scaffold: spec serving, retire a slot, admit an arrival,
    decode on; returns the generator (the _SPEC_ADMIT_STREAMS ids live)."""
    g = BG(CFG, params, plan=plan, settings=settings, spec_k=4,
           admit_chunk=8)
    g.set_prompts([list(_SPEC_ADMIT_STREAMS[0][1]), [3, 1, 4, 1]],
                  stream_ids=[0, 1])
    for _ in range(3):
        g.step()
    g.streams[1].done = True
    g.enqueue(list(_SPEC_ADMIT_STREAMS[1][1]), stream_id=9)
    while g.pending_admissions():
        g.step()
    for _ in range(14):
        g.step()
    return g


def _assert_matches_solo_spec(params, settings, g, sid, prompt):
    got = next(s for s in g.streams
               if s.active and s.stream_id == sid).generated
    solo = BG(CFG, params, settings=settings, spec_k=4)
    solo.set_prompts([list(prompt)], stream_ids=[sid])
    want = solo.generate(len(got))[0]
    assert got == want[: len(got)] and got, sid


def test_serving_speculation_composes_with_admission(params):
    """enqueue during spec serving: the admitted stream's tokens match the
    same (seed, stream_id, prompt) served solo with speculation."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    g = _drive_spec_admission(params, settings)
    _assert_matches_solo_spec(params, settings, g,
                              *_SPEC_ADMIT_STREAMS[1])


def test_serving_speculation_with_int8_kv(params):
    """spec_k composes with the quantized KV cache."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    prompts = [[5, 9, 2, 5, 9, 2], [3, 1, 4, 1]]
    plain = BG(CFG, params, settings=settings, kv_quant="int8")
    plain.set_prompts([list(p) for p in prompts])
    want = plain.generate(8)
    spec = BG(CFG, params, settings=settings, kv_quant="int8", spec_k=4)
    spec.set_prompts([list(p) for p in prompts])
    assert spec.generate(8) == want


def test_generate_is_incremental(params):
    """Repeated generate(N) calls continue the streams — N MORE tokens
    each call (the pre-r4 contract, preserved by the ragged-emission
    rewrite)."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings)
    g.set_prompts([[5, 9, 2], [3, 1, 4]])
    first = [list(s) for s in g.generate(4)]
    assert all(len(s) == 4 for s in first)
    second = g.generate(3)
    assert all(len(s) == 7 for s in second)
    for a, b in zip(first, second):
        assert b[:4] == a
    # same for the speculative path
    gs = BG(CFG, params, settings=settings, spec_k=4)
    gs.set_prompts([[5, 9, 2, 5, 9, 2], [3, 1, 4, 1]])
    f = [list(s) for s in gs.generate(4)]
    s2 = gs.generate(3)
    assert all(len(x) == 7 for x in s2)
    for a, b in zip(f, s2):
        assert b[:4] == a


@pytest.mark.parametrize("kv_quant", [None, "int8"])
def test_staged_batch_prefill_uses_pipelined_chunks(params, kv_quant):
    """On a staged mesh, set_prompts' batch prefill streams prompt chunks
    through the stages (GPipe microbatch mode) when the bucket divides —
    streams bit-identical to the 1-stage serving oracle, with and without
    the quantized KV cache."""
    from cake_tpu.parallel.mesh import MeshPlan

    settings = SamplerSettings(**GREEDY)
    prompts = [[5, 9, 2, 11, 3, 8], [3, 1, 4, 1, 5, 9], [7, 7, 2, 4]]
    flat = BG(CFG, params, settings=settings, kv_quant=kv_quant)
    flat.set_prompts([list(p) for p in prompts])
    want = flat.generate(8)
    plan = MeshPlan.build(CFG, num_stages=2, devices=jax.devices()[:2])
    staged = BG(CFG, params, plan=plan, settings=settings,
                kv_quant=kv_quant)
    staged.set_prompts([list(p) for p in prompts])
    assert staged._BatchGenerator__prefill_pipelined is not None
    assert staged.generate(8) == want


def test_spec_admission_staged_mesh_triple_composition(params):
    """The full r4 serving stack at once: staged mesh (interleaved verify +
    decode fallback), batched speculation, and continuous admission — the
    admitted stream and the survivors all match their solo oracles."""
    from cake_tpu.parallel.mesh import MeshPlan

    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    plan = MeshPlan.build(CFG, num_stages=2, devices=jax.devices()[:2])
    g = _drive_spec_admission(params, settings, plan=plan)
    assert g.stats()["spec_dispatches"] >= 1
    for sid, prompt in _SPEC_ADMIT_STREAMS:
        _assert_matches_solo_spec(params, settings, g, sid, prompt)


def test_spec_with_block_decode_preserves_emission_order(params):
    """spec_k composed with block_size > 1 (the CLI serving default): a
    spec round must never run while fused-block rows are still buffered,
    or later tokens would emit before buffered earlier ones (r4 review
    repro — the proposal-less first steps fall to the block path, then
    proposals appear mid-drain)."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompts = [[5, 9, 2, 5, 9, 2, 5, 9], [7, 7, 2, 8]]
    plain = BG(CFG, params, settings=settings)
    plain.set_prompts([list(p) for p in prompts])
    want = plain.generate(12)
    for block in (2, 4):
        g = BG(CFG, params, settings=settings, spec_k=4, block_size=block)
        g.set_prompts([list(p) for p in prompts])
        assert g.generate(12) == want, block


def test_generate_quota_under_skewed_acceptance(params):
    """One repetitive stream banking K+1 tokens per round must not starve
    a non-repetitive stream of its generate(N) quota (the safety cap
    scales with spec_k)."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    g = BG(CFG, params, settings=settings, spec_k=8)
    g.set_prompts([[5, 9, 2, 5, 9, 2, 5, 9], [7, 3, 8, 1]])
    outs = g.generate(6)
    assert all(len(o) == 6 for o in outs), [len(o) for o in outs]


def test_warm_admission_requires_pin_with_int8(params):
    from cake_tpu.ops.quant import quantize_params

    qp = quantize_params(params)
    settings = SamplerSettings(temperature=0.9, top_k=10)
    g = BG(CFG, qp, settings=settings)
    with pytest.raises(ValueError, match="backend pin"):
        g.warm_admission(8)
    # explicit pin or set_prompts-first both unblock it
    g2 = BG(CFG, qp, settings=settings, quant_backend="xla")
    g2.warm_admission(8)
    g3 = BG(CFG, qp, settings=settings)
    g3.set_prompts([[5, 9, 2]])
    g3.warm_admission(8)


def test_spec_serving_with_prefix_store_hit(params):
    """Speculation x prefix store: an arrival admitted through a prefix-
    cache HIT joins a speculating batch and still matches its solo spec
    oracle (the banked prefix row and the spec verify touch the same
    cache rows)."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    sysp = [(i * 7) % 100 + 2 for i in range(16)]
    g = BG(CFG, params, settings=settings, spec_k=4, admit_chunk=8,
           prefix_share_min=8, prefix_block=8)
    g.set_prompts([sysp + [5, 9, 2], sysp + [3, 1, 4]], stream_ids=[0, 1])
    for _ in range(3):
        g.step()
    g.streams[1].done = True
    new_prompt = sysp + [8, 8, 4]
    d0 = g.stats()["admit_dispatches"]
    g.enqueue(list(new_prompt), stream_id=9)
    while g.pending_admissions():
        g.step()
    assert g.stats()["admit_dispatches"] - d0 == 1  # prefix hit: 1 chunk
    assert g.stats()["prefix_hits"] >= 1
    for _ in range(10):
        g.step()
    _assert_matches_solo_spec(params, settings, g, 9, new_prompt)


def test_spec_chain_syncs_once_per_rounds_and_matches_host_loop(params):
    """spec_rounds=8 (fused chain) must emit the same greedy streams as
    spec_rounds=1 (per-round host loop) with ~rounds fewer syncs, and the
    chain must actually engage (spec_chains > 0)."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    cfg = tiny(max_seq_len=256, eos_token_id=-1)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompts = [[5, 9, 2, 5, 9, 2, 5, 9], [7, 1, 3, 7, 1, 3, 7, 1]]

    def run(rounds):
        g = BatchGenerator(cfg, params, settings=settings, spec_k=4,
                           spec_rounds=rounds)
        g.set_prompts([list(p) for p in prompts])
        for _ in range(30):
            g.step()
        return [list(s.generated[:28]) for s in g.streams], g.stats()

    want, st_host = run(1)
    got, st_fused = run(8)
    # the chain banks more tokens per step() call, so 30 steps yield
    # different counts; greedy bit-identity is on the common prefix
    for g_row, w_row in zip(got, want):
        n = min(len(g_row), len(w_row))
        assert n >= 20
        assert g_row[:n] == w_row[:n]
    assert st_host["spec_chains"] == 0
    assert st_fused["spec_chains"] >= 1


def test_adaptive_block_bit_identical(params):
    """The adaptive ladder (block doubling on an empty arrival queue) must
    not change any stream's greedy output — same per-row positions and
    in-program key schedule regardless of dispatch granularity."""
    settings = SamplerSettings(**GREEDY)
    want = [_single_stream(params, p, 12, settings) for p in PROMPTS]
    got = _batch_run(params, PROMPTS, 12, settings, dp=1, block_size=2,
                     block_size_max=8)
    assert got == want


def test_adaptive_block_sampled_invariant(params):
    """Sampled streams too: the per-row absolute token index keys every
    draw, so ladder growth cannot perturb the sampling schedule."""
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=11)
    assert (
        _batch_run(params, PROMPTS, 8, settings, dp=1, block_size=2,
                   block_size_max=8)
        == _batch_run(params, PROMPTS, 8, settings, dp=1)
    )


def test_adaptive_block_grows_then_snaps_back_on_arrival(params):
    """The ladder doubles while no arrival waits and snaps back to the
    base block the moment one is queued (admission latency stays one base
    block), then the admitted stream is bit-identical to its solo run."""
    settings = SamplerSettings(**GREEDY)
    cfg = tiny(max_seq_len=64, eos_token_id=-1)
    g = BG(cfg, params, settings=settings, block_size=2, block_size_max=8)
    g.set_prompts([list(PROMPTS[0]), list(PROMPTS[1])])
    for _ in range(8):
        g.step()
    # queue empty for several dispatches: the ladder grew past the base
    assert g._adaptive > g.block_size
    g.streams[0].done = True
    g.enqueue(list(PROMPTS[2]), stream_id=7)
    live_pos = [g._pos[i] for i, s in enumerate(g.streams)
                if s.active and not s.done]
    assert g._pick_block_size(live_pos) == g.block_size  # snap-back
    for _ in range(40):
        g.step()
        if all(s.done or not s.active for s in g.streams):
            break
        if g.streams[0].stream_id == 7 and len(
                g.streams[0].generated) >= 6:
            break
    admitted = next(s for s in g.streams if s.stream_id == 7)
    gen7 = LlamaGenerator(cfg, params, settings=settings)
    gen7.set_prompt(list(PROMPTS[2]))
    # stream_id drives the key; greedy here so id does not matter
    want = [gen7.next_token(i).id for i in range(len(admitted.generated))]
    assert admitted.generated == want[:len(admitted.generated)]
    assert len(admitted.generated) >= 4


def test_adaptive_block_headroom_cap_near_window(params):
    """Streams near their window edge must halve the grown block back down
    the ladder instead of dispatching mostly clamped overrun writes; every
    stream still fills its window exactly."""
    settings = SamplerSettings(**GREEDY)
    cfg = tiny(max_seq_len=32, eos_token_id=-1)
    g = BG(cfg, params, settings=settings, block_size=2, block_size_max=16)
    g.set_prompts([[5, 9, 2, 11], [3, 1, 4, 1]])
    single = LlamaGenerator(cfg, params, settings=settings)
    single.set_prompt([5, 9, 2, 11])
    n = 32 - 4  # window minus prompt
    want = [single.next_token(i).id for i in range(n)]
    out = g.generate(n)
    assert out[0] == want
    assert all(s.done for s in g.streams)  # window-full, cleanly


def test_warm_blocks_precompiles_ladder(params):
    """warm_blocks compiles every ladder rung outside the serving window
    and leaves the live state untouched (outputs discarded)."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, block_size=2, block_size_max=8)
    g.set_prompts([list(p) for p in PROMPTS])
    before = [list(s.generated) for s in g.streams]
    g.warm_blocks()
    assert [list(s.generated) for s in g.streams] == before
    progs = g._BatchGenerator__block_progs
    assert {s for s, _ in progs} == {4, 8}
    want = [_single_stream(params, p, 10, settings) for p in PROMPTS]
    assert g.generate(10) == want


def test_block_size_max_rounds_down_to_ladder(params):
    """A non-power-of-two max rounds down to base*2^k so the headroom
    halving always lands on a compiled rung."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, block_size=3, block_size_max=13)
    assert g.block_size_max == 12
    g = BG(CFG, params, settings=settings, block_size=4, block_size_max=4)
    assert g.block_size_max == 4
    g = BG(CFG, params, settings=settings, block_size=4)
    assert g.block_size_max == 4


def test_lookahead_dispatch_bit_identical_with_admission(params):
    """r5: lookahead double-buffering (dispatch block N+1 before fetching
    block N) must not change any stream's tokens — the device feedback
    token is exactly the host's, and an admission mid-flight drains the
    in-flight block's rows before the slot changes meaning."""
    settings = SamplerSettings(**GREEDY)
    new_prompt = [2, 8, 1, 7, 6, 5, 4, 3]

    def run(look):
        g = BG(CFG, params, settings=settings, block_size=2,
               block_size_max=8, lookahead=look, admit_chunk=4)
        g.set_prompts([list(PROMPTS[0]), list(PROMPTS[1])])
        for _ in range(6):
            g.step()
        if look:
            assert g._inflight is not None  # the pipeline actually engaged
        g.streams[0].done = True
        g.enqueue(list(new_prompt), stream_id=7)
        for _ in range(16):
            g.step()
        return {s.stream_id: list(s.generated) for s in g.streams}

    got, want = run(True), run(False)
    assert set(got) == set(want) == {1, 7}
    for sid in got:
        n = min(len(got[sid]), len(want[sid]))
        assert n >= 4 and got[sid][:n] == want[sid][:n]


def test_lookahead_rejects_speculation(params):
    settings = SamplerSettings(**GREEDY)
    with pytest.raises(ValueError, match="lookahead"):
        BG(CFG, params, settings=settings, lookahead=True, spec_k=4)


def test_lookahead_drain_emits_inflight_tokens(params):
    """drain() at a measurement/shutdown boundary fetches the in-flight
    block without dispatching more; its tokens continue the stream's
    oracle sequence exactly."""
    settings = SamplerSettings(**GREEDY)
    g = BG(CFG, params, settings=settings, block_size=2, block_size_max=4,
           lookahead=True)
    g.set_prompts([list(PROMPTS[0])])
    for _ in range(4):
        g.step()
    assert g._inflight is not None
    dispatches_before = g.stats()["decode_dispatches"]
    before = len(g.streams[0].generated)
    g.drain()
    assert g._inflight is None and not g._block_buf
    got = list(g.streams[0].generated)
    assert len(got) > before
    assert g.stats()["decode_dispatches"] == dispatches_before  # no new work
    want = _single_stream(params, PROMPTS[0], len(got), settings)
    assert got == want[: len(got)]
