"""Sharded-vs-unsharded numerical parity on a virtual CPU mesh.

SURVEY.md §4: multi-device tests run on `--xla_force_host_platform_device_count=8`
CPU devices. Every mesh layout (pp-only, tp-only, pp x tp, + dp) must produce
the same logits/tokens as the plain single-device path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.kvcache import init_cache
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import MeshPlan, shard_cache, shard_params, validate_shardable
from cake_tpu.parallel.pipeline import build_sharded_decode, build_sharded_prefill
from cake_tpu.runtime.generator import prefill_fn


CFG = tiny(max_seq_len=32)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0))


def _reference_logits(params, ids):
    cache = init_cache(CFG, batch=1, max_seq=CFG.max_seq_len)
    logits, cache = llama.forward(
        params, jnp.asarray([ids], jnp.int32), cache, 0, CFG
    )
    return logits, cache


def _sharded_prefill_logits(params, ids, plan, batch=1):
    prefill = build_sharded_prefill(CFG, plan)
    sp = shard_params(params, plan.mesh)
    cache = shard_cache(
        init_cache(CFG, batch=batch, max_seq=CFG.max_seq_len), plan.mesh
    )
    tokens = jnp.tile(jnp.asarray([ids], jnp.int32), (batch, 1))
    last = jnp.full((batch,), len(ids) - 1, jnp.int32)
    logits, cache = prefill(sp, tokens, cache, last)
    return logits, cache, sp, prefill


@pytest.mark.parametrize(
    "stages,tp,dp",
    [(2, 1, 1), (4, 1, 1), (1, 2, 1), (2, 2, 1), (1, 1, 2), (2, 2, 2)],
)
def test_sharded_prefill_matches_unsharded(params, stages, tp, dp):
    plan = MeshPlan.build(CFG, num_stages=stages, tp=tp, dp=dp)
    ids = [3, 1, 4, 1, 5, 9, 2, 6]
    ref, _ = _reference_logits(params, ids)
    got, _, _, _ = _sharded_prefill_logits(params, ids, plan, batch=dp)
    for b in range(dp):
        np.testing.assert_allclose(
            np.asarray(got[b]), np.asarray(ref[0]), rtol=2e-4, atol=2e-4
        )


@pytest.mark.parametrize("stages,tp,dp", [(2, 2, 1), (4, 1, 1), (1, 2, 2)])
def test_sharded_greedy_decode_matches_unsharded(params, stages, tp, dp):
    """Full loop: sharded prefill + N greedy sharded decode steps produce the
    same token stream as the single-device generator math."""
    plan = MeshPlan.build(CFG, num_stages=stages, tp=tp, dp=dp)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    ids = [7, 3, 11, 2]
    n_steps = 4

    # reference: single-device greedy
    cache = init_cache(CFG, batch=1, max_seq=CFG.max_seq_len)
    logits, cache = llama.forward(
        params, jnp.asarray([ids], jnp.int32), cache, 0, CFG
    )
    expect = []
    pos = len(ids)
    for _ in range(n_steps):
        t = int(jnp.argmax(logits[0]))
        expect.append(t)
        logits, cache = llama.forward(
            params, jnp.asarray([[t]], jnp.int32), cache, pos, CFG
        )
        pos += 1

    # sharded
    batch = dp
    logits_s, cache_s, sp, _ = _sharded_prefill_logits(params, ids, plan, batch)
    decode = build_sharded_decode(CFG, settings, plan)
    history = jnp.full((batch, settings.repeat_last_n), -1, jnp.int32)
    hist_slot = jnp.int32(0)
    key = jax.random.PRNGKey(0)
    tok = jnp.argmax(logits_s, axis=-1).astype(jnp.int32)
    got = [tok]
    pos = jnp.int32(len(ids))
    for _ in range(n_steps - 1):
        tok, cache_s, history, hist_slot = decode(
            sp, tok, cache_s, pos, key, history, hist_slot
        )
        got.append(tok)
        pos += 1

    for b in range(batch):
        stream = [int(t[b]) for t in got]
        assert stream == expect, f"batch row {b}: {stream} != {expect}"


def test_validate_shardable_rejects_bad_splits():
    with pytest.raises(ValueError):
        validate_shardable(CFG, num_stages=3, tp=1)  # 4 layers % 3
    with pytest.raises(ValueError):
        validate_shardable(CFG, num_stages=1, tp=4)  # 2 kv heads % 4
    validate_shardable(CFG, num_stages=2, tp=2)


def test_mesh_needs_enough_devices():
    with pytest.raises(ValueError):
        MeshPlan.build(CFG, num_stages=4, tp=4, dp=4)


def test_from_topology_uniform_split():
    from cake_tpu.parallel.topology import Topology

    t = Topology.from_dict({
        "s0": {"device": 0, "layers": ["model.layers.0-1"]},
        "s1": {"device": 1, "layers": ["model.layers.2-3"]},
    })
    plan = MeshPlan.from_topology(CFG, t)
    assert plan.num_stages == 2


def test_from_topology_rejects_uneven_ranges():
    from cake_tpu.parallel.topology import Topology

    t = Topology.from_dict({
        "s0": {"device": 0, "layers": ["model.layers.0-2"]},  # 3 layers
        "s1": {"device": 1, "layers": ["model.layers.3"]},    # 1 layer
    })
    with pytest.raises(ValueError, match="uniform layer split"):
        MeshPlan.from_topology(CFG, t)


def test_from_topology_rejects_device_gaps():
    from cake_tpu.parallel.topology import Topology

    t = Topology.from_dict({
        "s0": {"device": 0, "layers": ["model.layers.0-1"]},
        "s1": {"device": 3, "layers": ["model.layers.2-3"]},
    })
    with pytest.raises(ValueError, match="no gaps"):
        MeshPlan.from_topology(CFG, t)


# ---------------------------------------------------------------------------
# Pipelined (GPipe-style) chunked prefill: prompt chunks stream through the
# stages concurrently. The reference explicitly has "no micro-batching and no
# pipelining overlap" (SURVEY.md §2) — upstream workers idle.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "stages,tp,dp,microbatch",
    [(2, 1, 1, 2), (2, 1, 1, 4), (4, 1, 1, 4), (2, 2, 1, 4), (2, 1, 2, 2),
     (2, 2, 2, 4)],
)
def test_pipelined_prefill_matches_unsharded(params, stages, tp, dp,
                                             microbatch):
    plan = MeshPlan.build(CFG, num_stages=stages, tp=tp, dp=dp)
    ids = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
    ref, _ = _reference_logits(params, ids)
    prefill = build_sharded_prefill(CFG, plan, microbatch=microbatch)
    sparams = shard_params(params, plan.mesh)
    cache = shard_cache(
        init_cache(CFG, batch=dp, max_seq=CFG.max_seq_len), plan.mesh
    )
    tokens = jnp.tile(jnp.asarray([ids + [0] * 4], jnp.int32), (dp, 1))
    last = jnp.full((dp,), len(ids) - 1, jnp.int32)
    logits, _ = prefill(sparams, tokens, cache, last)
    for b in range(dp):
        np.testing.assert_allclose(
            np.asarray(logits[b]), np.asarray(ref[0]), rtol=2e-4, atol=2e-4
        )


def test_pipelined_prefill_cache_feeds_decode(params):
    """The chunk-written KV must be exactly what decode attends over: the
    greedy continuation after pipelined prefill matches the unsharded run."""
    plan = MeshPlan.build(CFG, num_stages=2, tp=2)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    ids = [7, 3, 11, 2, 9, 1, 4, 6]

    cache = init_cache(CFG, batch=1, max_seq=CFG.max_seq_len)
    logits, cache = llama.forward(
        params, jnp.asarray([ids], jnp.int32), cache, 0, CFG
    )
    expect, pos = [], len(ids)
    for _ in range(4):
        t = int(jnp.argmax(logits[0]))
        expect.append(t)
        logits, cache = llama.forward(
            params, jnp.asarray([[t]], jnp.int32), cache, pos, CFG
        )
        pos += 1

    prefill = build_sharded_prefill(CFG, plan, microbatch=4)
    sparams = shard_params(params, plan.mesh)
    cache_s = shard_cache(
        init_cache(CFG, batch=1, max_seq=CFG.max_seq_len), plan.mesh
    )
    logits_s, cache_s = prefill(
        sparams, jnp.asarray([ids], jnp.int32), cache_s,
        jnp.asarray([len(ids) - 1], jnp.int32),
    )
    decode = build_sharded_decode(CFG, settings, plan)
    history = jnp.full((1, settings.repeat_last_n), -1, jnp.int32)
    tok = jnp.argmax(logits_s, axis=-1).astype(jnp.int32)
    got, pos = [tok], jnp.int32(len(ids))
    hist_slot = jnp.int32(0)
    for _ in range(3):
        tok, cache_s, history, hist_slot = decode(
            sparams, tok, cache_s, pos, jax.random.PRNGKey(0), history,
            hist_slot,
        )
        got.append(tok)
        pos += 1
    assert [int(t[0]) for t in got] == expect


# ---------------------------------------------------------------------------
# Sequence/context parallelism (sp axis): ring-attention prefill + distributed
# flash decode must match the single-device oracle. The reference has no
# long-context plane at all (SURVEY.md §5) — this is TPU-native capability.
# ---------------------------------------------------------------------------


def _padded(ids, batch=1, t_pad=None):
    """Pad the prompt to ``t_pad`` (default: full window). Chunked sp
    prefill only needs a multiple of sp — prompt-proportional, T ≪ max_seq."""
    t_pad = t_pad or CFG.max_seq_len
    full = ids + [0] * (t_pad - len(ids))
    return jnp.tile(jnp.asarray([full], jnp.int32), (batch, 1))


@pytest.mark.parametrize(
    "stages,tp,dp,sp,t_pad",
    [
        # chunked prefill: T=16 ≪ max_seq=32, per-shard chunk < cache slice
        (1, 1, 1, 2, 16), (1, 1, 1, 4, 16), (2, 1, 1, 2, 16),
        (2, 2, 1, 2, 16), (1, 2, 1, 4, 16), (1, 1, 2, 2, 16),
        # minimal bucket: T_l = 2 per shard
        (1, 1, 1, 4, 8),
        # full-window contract still works (t == s_l fast path)
        (2, 2, 1, 2, None), (1, 1, 1, 4, None),
    ],
)
def test_sp_prefill_matches_unsharded(params, stages, tp, dp, sp, t_pad):
    plan = MeshPlan.build(CFG, num_stages=stages, tp=tp, dp=dp, sp=sp)
    ids = [3, 1, 4, 1, 5, 9, 2, 6]
    ref, _ = _reference_logits(params, ids)

    prefill = build_sharded_prefill(CFG, plan)
    sparams = shard_params(params, plan.mesh)
    cache = shard_cache(
        init_cache(CFG, batch=dp, max_seq=CFG.max_seq_len), plan.mesh
    )
    last = jnp.full((dp,), len(ids) - 1, jnp.int32)
    logits, _ = prefill(
        sparams, _padded(ids, batch=dp, t_pad=t_pad), cache, last
    )
    for b in range(dp):
        np.testing.assert_allclose(
            np.asarray(logits[b]), np.asarray(ref[0]), rtol=2e-4, atol=2e-4
        )


@pytest.mark.parametrize("sp", [2, 4])
def test_sp_prefill_one_token_per_shard_chunk(params, sp):
    """T_pad == sp gives every shard a ONE-token prefill chunk; the explicit
    sp_prefill flag must keep it on the ring/chunked-write path (the T>1
    heuristic misrouted this to decode — silently wrong logits, r2
    code-review finding)."""
    plan = MeshPlan.build(CFG, sp=sp)
    ids = [3, 1] if sp == 2 else [3, 1, 4]
    ref, _ = _reference_logits(params, ids)
    prefill = build_sharded_prefill(CFG, plan)
    sparams = shard_params(params, plan.mesh)
    cache = shard_cache(init_cache(CFG, batch=1, max_seq=CFG.max_seq_len),
                        plan.mesh)
    logits, _ = prefill(
        sparams, _padded(ids, t_pad=sp), cache,
        jnp.asarray([len(ids) - 1], jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), np.asarray(ref[0]), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("stages,tp,dp,sp", [(1, 1, 1, 4), (2, 1, 1, 2),
                                             (1, 2, 1, 2)])
def test_sp_greedy_decode_matches_unsharded(params, stages, tp, dp, sp):
    plan = MeshPlan.build(CFG, num_stages=stages, tp=tp, dp=dp, sp=sp)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    ids = [7, 3, 11, 2]
    n_steps = 4

    cache = init_cache(CFG, batch=1, max_seq=CFG.max_seq_len)
    logits, cache = llama.forward(
        params, jnp.asarray([ids], jnp.int32), cache, 0, CFG
    )
    expect = []
    pos = len(ids)
    for _ in range(n_steps):
        t = int(jnp.argmax(logits[0]))
        expect.append(t)
        logits, cache = llama.forward(
            params, jnp.asarray([[t]], jnp.int32), cache, pos, CFG
        )
        pos += 1

    prefill = build_sharded_prefill(CFG, plan)
    sparams = shard_params(params, plan.mesh)
    cache_s = shard_cache(
        init_cache(CFG, batch=dp, max_seq=CFG.max_seq_len), plan.mesh
    )
    last = jnp.full((dp,), len(ids) - 1, jnp.int32)
    # chunked prefill (T=8 ≪ max_seq) feeding decode: the chunked cache
    # write must land KV exactly where sp decode attends for it
    logits_s, cache_s = prefill(
        sparams, _padded(ids, batch=dp, t_pad=8), cache_s, last
    )

    decode = build_sharded_decode(CFG, settings, plan)
    history = jnp.full((dp, settings.repeat_last_n), -1, jnp.int32)
    hist_slot = jnp.int32(0)
    key = jax.random.PRNGKey(0)
    tok = jnp.argmax(logits_s, axis=-1).astype(jnp.int32)
    got = [tok]
    pos = jnp.int32(len(ids))
    for _ in range(n_steps - 1):
        tok, cache_s, history, hist_slot = decode(
            sparams, tok, cache_s, pos, key, history, hist_slot
        )
        got.append(tok)
        pos += 1

    for b in range(dp):
        stream = [int(t[b]) for t in got]
        assert stream == expect, f"batch row {b}: {stream} != {expect}"


def test_sp_validate_rejects_indivisible_window():
    with pytest.raises(ValueError, match="sp"):
        validate_shardable(tiny(max_seq_len=30), num_stages=1, tp=1, sp=4)


def test_70b_and_8b_shardability_envelopes():
    """The BASELINE deployment shapes divide cleanly: 8B across 4 stages
    (config 3) and 70B across 16 stages with tp/sp (configs 4-5)."""
    from cake_tpu.models.config import llama3_70b, llama3_8b
    from cake_tpu.parallel.mesh import validate_shardable

    validate_shardable(llama3_8b(), num_stages=4, tp=1)
    validate_shardable(llama3_8b(), num_stages=4, tp=2, sp=2)
    c70 = llama3_70b()
    validate_shardable(c70, num_stages=16, tp=1)
    validate_shardable(c70, num_stages=16, tp=4, sp=4)
    validate_shardable(c70, num_stages=8, tp=8, sp=2)
    with pytest.raises(ValueError, match="not divisible"):
        validate_shardable(c70, num_stages=3, tp=1)
