"""Checkpoint fetch plane (CLI --fetch / utils.fetch).

Replaces the reference's hub convenience WITHOUT its quirk: the reference
re-downloads `meta-llama/Meta-Llama-3-8B` on every master start even when
--model points at local files (cake/mod.rs:80-96, local loading commented
out). Here fetch is explicit and idempotent; hub access is exercised via a
stub (zero-egress environment)."""

import json
from pathlib import Path

import pytest

from cake_tpu.utils.fetch import DEFAULT_PATTERNS, fetch_checkpoint


@pytest.fixture()
def src_dir(tmp_path):
    d = tmp_path / "src"
    d.mkdir()
    (d / "config.json").write_text(json.dumps({"hidden_size": 64}))
    (d / "tokenizer.json").write_text("{}")
    (d / "model.safetensors").write_bytes(b"\x00" * 16)
    (d / "model.safetensors.index.json").write_text("{}")
    (d / "README.md").write_text("not an inference file")
    return d


def test_local_fetch_copies_inference_set(src_dir, tmp_path):
    dest = fetch_checkpoint(f"file://{src_dir}", tmp_path / "model")
    names = sorted(p.name for p in dest.iterdir())
    assert names == ["config.json", "model.safetensors",
                     "model.safetensors.index.json", "tokenizer.json"]
    # README filtered out: only the inference file set travels
    assert not (dest / "README.md").exists()


def test_fetch_is_idempotent_not_forced(src_dir, tmp_path):
    """Unlike the reference's always-re-download, present files are kept."""
    dest = tmp_path / "model"
    fetch_checkpoint(str(src_dir), dest)
    marker = dest / "config.json"
    marker.write_text("locally edited")
    fetch_checkpoint(str(src_dir), dest)
    assert marker.read_text() == "locally edited"
    fetch_checkpoint(str(src_dir), dest, force=True)
    assert marker.read_text() != "locally edited"


def test_missing_source_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        fetch_checkpoint(str(tmp_path / "nope"), tmp_path / "model")


def test_hub_fetch_wiring(tmp_path, monkeypatch):
    """hf:// parses repo@revision and calls snapshot_download with the
    inference allow-list (stubbed: zero-egress environment)."""
    calls = {}

    def fake_snapshot_download(repo_id, revision, local_dir, allow_patterns):
        calls.update(repo_id=repo_id, revision=revision, local_dir=local_dir,
                     allow_patterns=allow_patterns)
        Path(local_dir, "config.json").write_text("{}")

    import huggingface_hub

    monkeypatch.setattr(huggingface_hub, "snapshot_download",
                        fake_snapshot_download)
    dest = fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B@main",
                            tmp_path / "model")
    assert calls["repo_id"] == "meta-llama/Meta-Llama-3-8B"
    assert calls["revision"] == "main"
    assert set(DEFAULT_PATTERNS) <= set(calls["allow_patterns"])
    assert (dest / "config.json").exists()


def test_hub_fetch_skips_when_stamped_complete(tmp_path, monkeypatch):
    """A checkout the fetcher itself completed (stamp + config + weights)
    skips the network entirely — warm offline runs keep working."""
    dest = tmp_path / "model"
    dest.mkdir()
    (dest / "config.json").write_text("{}")
    (dest / "model.safetensors").write_bytes(b"\x00")
    (dest / ".cake_fetched").write_text("meta-llama/Meta-Llama-3-8B")

    def boom(**kw):  # pragma: no cover - must not be reached
        raise AssertionError("hub hit despite populated dir")

    import huggingface_hub

    monkeypatch.setattr(huggingface_hub, "snapshot_download", boom)
    fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B", dest)


def test_hub_fetch_repairs_partial_checkout(tmp_path, monkeypatch):
    """An interrupted download (no completion stamp) re-consults the hub
    (incremental) and self-repairs; success writes the stamp so the next
    run skips."""
    dest = tmp_path / "model"
    dest.mkdir()
    (dest / "config.json").write_text("{}")
    (dest / "model.safetensors").write_bytes(b"\x00")
    calls = {"n": 0}

    def fake(repo_id, revision, local_dir, allow_patterns):
        calls["n"] += 1
        Path(local_dir, "tokenizer.json").write_text("{}")

    import huggingface_hub

    monkeypatch.setattr(huggingface_hub, "snapshot_download", fake)
    fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B", dest)
    assert calls["n"] == 1 and (dest / "tokenizer.json").exists()
    assert (dest / ".cake_fetched").read_text() == "meta-llama/Meta-Llama-3-8B"
    fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B", dest)
    assert calls["n"] == 1  # stamped: second run skipped the hub


def test_hub_fetch_movable_revision_always_reconsults(tmp_path, monkeypatch):
    """A branch/tag pin (movable revision) consults the hub every time —
    even when stamped — or it would silently track a stale tip forever.
    Commit-hash pins and unpinned fetches may stamp-skip."""
    dest = tmp_path / "model"
    dest.mkdir()
    (dest / "config.json").write_text("{}")
    (dest / "model.safetensors").write_bytes(b"\x00")
    (dest / ".cake_fetched").write_text("meta-llama/Meta-Llama-3-8B")
    calls = {"n": 0}

    import huggingface_hub

    monkeypatch.setattr(
        huggingface_hub, "snapshot_download",
        lambda **kw: calls.update(n=calls["n"] + 1),
    )
    fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B@v2", dest)
    assert calls["n"] == 1
    assert (dest / ".cake_fetched").read_text() == "meta-llama/Meta-Llama-3-8B@v2"
    # movable pin: hits the hub again despite the matching stamp
    fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B@v2", dest)
    assert calls["n"] == 2
    # immutable commit-hash pin: stamp-skips once stamped
    fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B@abc123def4", dest)
    assert calls["n"] == 3
    fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B@abc123def4", dest)
    assert calls["n"] == 3


def _legacy_dest(tmp_path, cfg="{}"):
    dest = tmp_path / "model"
    dest.mkdir()
    (dest / "config.json").write_text(cfg)
    (dest / "tokenizer.json").write_text("{}")
    (dest / "model.safetensors").write_bytes(b"\x00")
    return dest


def test_hub_fetch_legacy_unstamped_checkout_verified_then_stamped(
        tmp_path, monkeypatch):
    """A complete pre-stamp-era checkout (config + tokenizer + weights, no
    stamp) is identity-checked against the hub's config.json (one small
    file, not the weights), then stamped so later runs skip the hub."""
    dest = _legacy_dest(tmp_path, json.dumps({"hidden_size": 64}))

    def boom(**kw):  # pragma: no cover - must not be reached
        raise AssertionError("full snapshot hit for a complete checkout")

    def fake_cfg(repo_id, revision, filename, local_dir):
        p = Path(local_dir, filename)
        p.write_text(json.dumps({"hidden_size": 64}))
        return str(p)

    import huggingface_hub

    monkeypatch.setattr(huggingface_hub, "snapshot_download", boom)
    monkeypatch.setattr(huggingface_hub, "hf_hub_download", fake_cfg)
    fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B", dest)
    assert (dest / ".cake_fetched").read_text() == "meta-llama/Meta-Llama-3-8B"


def test_hub_fetch_legacy_unstamped_wrong_model_refused(tmp_path, monkeypatch):
    """An unstamped complete checkout of a DIFFERENT model must not be
    silently served and mislabeled as the requested repo (it errors and is
    left unstamped)."""
    dest = _legacy_dest(tmp_path, json.dumps({"hidden_size": 64}))

    def fake_cfg(repo_id, revision, filename, local_dir):
        p = Path(local_dir, filename)
        p.write_text(json.dumps({"hidden_size": 8192}))
        return str(p)

    import huggingface_hub

    monkeypatch.setattr(huggingface_hub, "hf_hub_download", fake_cfg)
    with pytest.raises(RuntimeError, match="does not match"):
        fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B", dest)
    assert not (dest / ".cake_fetched").exists()


def test_hub_fetch_legacy_unstamped_offline_used_but_not_stamped(
        tmp_path, monkeypatch):
    """Hub unreachable: the unstamped checkout still serves this run (warm
    offline runs keep working) but is NOT stamped — the next online run
    verifies identity before labeling the dir."""
    dest = _legacy_dest(tmp_path)

    import huggingface_hub

    monkeypatch.setattr(
        huggingface_hub, "hf_hub_download",
        lambda **kw: (_ for _ in ()).throw(ConnectionError("offline")),
    )
    out = fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B", dest)
    assert out == dest
    assert not (dest / ".cake_fetched").exists()


def test_hub_fetch_strict_mode_refuses_unverified_offline(
        tmp_path, monkeypatch):
    """CAKE_FETCH_STRICT=1 closes the offline serve-model-B-as-A window:
    an unstamped checkout that cannot be verified (hub unreachable) is
    refused instead of served-with-warning."""
    dest = _legacy_dest(tmp_path)

    import huggingface_hub

    monkeypatch.setattr(
        huggingface_hub, "hf_hub_download",
        lambda **kw: (_ for _ in ()).throw(ConnectionError("offline")),
    )
    monkeypatch.setenv("CAKE_FETCH_STRICT", "1")
    with pytest.raises(RuntimeError, match="CAKE_FETCH_STRICT"):
        fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B", dest)
    assert not (dest / ".cake_fetched").exists()


def test_hub_fetch_interrupted_refetch_invalidates_stamp(tmp_path, monkeypatch):
    """A download dying mid-refetch must not leave the old stamp certifying
    a mixed checkout: the stamp is unlinked before the hub call."""
    dest = tmp_path / "model"
    dest.mkdir()
    (dest / "config.json").write_text("{}")
    (dest / "model.safetensors").write_bytes(b"\x00")
    (dest / ".cake_fetched").write_text("meta-llama/Meta-Llama-3-8B")

    import huggingface_hub

    def dies(**kw):
        raise ConnectionError("network died mid-download")

    monkeypatch.setattr(huggingface_hub, "snapshot_download", dies)
    with pytest.raises(ConnectionError):
        fetch_checkpoint("hf://meta-llama/Meta-Llama-3-8B", dest, force=True)
    assert not (dest / ".cake_fetched").exists()
