import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.kvcache import init_cache
from cake_tpu.ops.rope import rope_tables


def _full_logits(config, params, tokens):
    """Forward the whole sequence at once (fresh cache), logits at last pos."""
    cache = init_cache(config, batch=1, max_seq=config.max_seq_len)
    logits, _ = llama.forward(params, tokens, cache, 0, config)
    return logits


def _mha_tiny():
    """Llama-2-class MHA geometry (kv_heads == heads, GQA group 1) at tiny
    dims — exercises the group=1 attention path."""
    from cake_tpu.models.config import llama2_7b

    return llama2_7b(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_seq_len=32, dtype="float32",
    )


@pytest.mark.parametrize("family", ["gqa", "mha"])
def test_prefill_then_decode_matches_full_forward(tiny_config, tiny_params,
                                                  family):
    """KV-cache correctness: incremental decode must equal full-context
    forward, for both GQA (Llama-3) and MHA/group-1 (Llama-2) attention.
    This is the core invariant the reference never tests (SURVEY.md §4)."""
    if family == "gqa":
        cfg, params = tiny_config, tiny_params
    else:
        cfg = _mha_tiny()
        assert cfg.num_attention_heads == cfg.num_key_value_heads
        params = llama.init_params(cfg, jax.random.PRNGKey(2))
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, size=10).tolist()

    # Incremental: prefill 6 tokens, then decode 4 one at a time.
    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
    logits, cache = llama.forward(
        params, jnp.asarray([ids[:6]], jnp.int32), cache, 0, cfg
    )
    for i in range(6, 10):
        logits, cache = llama.forward(
            params, jnp.asarray([[ids[i]]], jnp.int32), cache, i, cfg
        )

    full = _full_logits(cfg, params, jnp.asarray([ids + []], jnp.int32))
    # logits after feeding ids[9] at pos 9 == full-forward last-position logits
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full), rtol=2e-4, atol=2e-4
    )


def test_scan_matches_python_loop(tiny_config, tiny_params):
    """lax.scan over stacked layers == explicit per-layer loop."""
    cfg, params = tiny_config, tiny_params
    x = jax.random.normal(
        jax.random.PRNGKey(5), (1, 7, cfg.hidden_size), jnp.float32
    )
    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
    cos, sin = rope_tables(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)

    scanned, _ = llama.forward_layers(params["layers"], x, cache, cos, sin, 0, cfg)

    h = x
    for i in range(cfg.num_hidden_layers):
        layer_i = jax.tree.map(lambda a: a[i], params["layers"])
        h, _, _ = llama.block_forward(
            layer_i, h, cache.k[i], cache.v[i], cos, sin, 0, cfg
        )
    np.testing.assert_allclose(np.asarray(scanned), np.asarray(h), rtol=1e-5, atol=1e-5)


def test_causal_mask_future_independence(tiny_config, tiny_params):
    """Changing a future token must not change logits at an earlier position
    of the same full-sequence forward (true causality, not just finiteness)."""
    from cake_tpu.runtime.generator import prefill_fn

    cfg, params = tiny_config, tiny_params
    ids_a = [3, 5, 7, 9, 11]
    ids_b = [3, 5, 7, 9, 200]  # same prefix, different final token

    def logits_at(ids, index):
        cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
        logits, _ = prefill_fn(
            params,
            jnp.asarray([ids], jnp.int32),
            cache,
            jnp.asarray([index], jnp.int32),
            cfg,
        )
        return np.asarray(logits)

    # At position 3 (before the differing token) logits must be identical.
    np.testing.assert_array_equal(logits_at(ids_a, 3), logits_at(ids_b, 3))
    # At the final position they must differ.
    assert not np.allclose(logits_at(ids_a, 4), logits_at(ids_b, 4))


def test_forward_layers_subset_composes(tiny_config, tiny_params):
    """Running layers [0,2) then [2,4) equals running [0,4) — the invariant
    behind topology layer-sharding (worker executes its range only,
    worker.rs:208-219)."""
    cfg, params = tiny_config, tiny_params
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 5, cfg.hidden_size))
    cos, sin = rope_tables(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)

    full, _ = llama.forward_layers(params["layers"], x, cache, cos, sin, 0, cfg)

    first = jax.tree.map(lambda a: a[:2], params["layers"])
    second = jax.tree.map(lambda a: a[2:], params["layers"])
    from cake_tpu.ops.kvcache import KVCache

    c1 = KVCache(k=cache.k[:2], v=cache.v[:2])
    c2 = KVCache(k=cache.k[2:], v=cache.v[2:])
    h, _ = llama.forward_layers(first, x, c1, cos, sin, 0, cfg)
    h, _ = llama.forward_layers(second, h, c2, cos, sin, 0, cfg)
    np.testing.assert_allclose(np.asarray(full), np.asarray(h), rtol=1e-5, atol=1e-5)


def test_logits_are_f32(tiny_config, tiny_params):
    cfg, params = tiny_config, tiny_params
    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
    logits, _ = llama.forward(
        params, jnp.asarray([[1, 2, 3]], jnp.int32), cache, 0, cfg
    )
    assert logits.dtype == jnp.float32
    assert logits.shape == (1, cfg.vocab_size)


def test_llama2_7b_preset_real_geometry():
    from cake_tpu.models.config import llama2_7b

    cfg = llama2_7b()
    assert (cfg.vocab_size, cfg.hidden_size, cfg.intermediate_size) == (
        32000, 4096, 11008)
    assert cfg.num_attention_heads == cfg.num_key_value_heads == 32
    assert cfg.head_dim == 128 and cfg.rope_theta == 10000.0
