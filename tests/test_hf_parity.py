"""Golden parity against HF transformers' Llama implementation.

The strongest correctness anchor available offline (SURVEY.md §4): build a
tiny random Llama in torch/transformers, port the weights through the real
checkpoint-conversion path, and require logit agreement in f32.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from cake_tpu.models import llama  # noqa: E402
from cake_tpu.models.config import LlamaConfig  # noqa: E402
from cake_tpu.ops.kvcache import init_cache  # noqa: E402
from cake_tpu.utils.weights import params_from_hf_tensors  # noqa: E402


@pytest.fixture(scope="module")
def hf_model_and_config():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        attention_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig.from_hf_dict(hf_cfg.to_dict(), dtype="float32", max_seq_len=128)
    return model, cfg


def _port_params(model, cfg):
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    return params_from_hf_tensors(
        sd.__getitem__, cfg.num_hidden_layers, dtype="float32"
    )


def test_logits_match_transformers(hf_model_and_config):
    model, cfg = hf_model_and_config
    params = _port_params(model, cfg)
    ids = [5, 17, 42, 99, 7, 3]

    with torch.no_grad():
        ref = model(torch.tensor([ids])).logits[0, -1].numpy()

    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
    got, _ = llama.forward(params, jnp.asarray([ids], jnp.int32), cache, 0, cfg)
    np.testing.assert_allclose(np.asarray(got[0]), ref, rtol=2e-4, atol=2e-4)


def test_incremental_decode_matches_transformers(hf_model_and_config):
    model, cfg = hf_model_and_config
    params = _port_params(model, cfg)
    ids = [5, 17, 42, 99, 7, 3, 88, 120]

    with torch.no_grad():
        ref_all = model(torch.tensor([ids])).logits[0].numpy()

    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
    # prefill 4, then decode the rest one at a time; compare each step's
    # logits with the full-context HF forward at the same position.
    logits, cache = llama.forward(
        params, jnp.asarray([ids[:4]], jnp.int32), cache, 0, cfg
    )
    np.testing.assert_allclose(np.asarray(logits[0]), ref_all[3], rtol=2e-4, atol=2e-4)
    for i in range(4, len(ids)):
        logits, cache = llama.forward(
            params, jnp.asarray([[ids[i]]], jnp.int32), cache, i, cfg
        )
        np.testing.assert_allclose(
            np.asarray(logits[0]), ref_all[i], rtol=2e-4, atol=2e-4
        )
