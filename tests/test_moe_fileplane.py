"""Mixtral weight-plane rehearsal at FILE scale.

The MoE twin of tests/test_70b_fileplane.py (the reference's offline
weight plane is `cake-split-model`, main.rs:144-223): a pre-quantized
multi-shard int8 MoE checkpoint loads direct-to-mesh over a
stage=2 x ep=2 mesh, and byte accounting proves

- each ep rank's expert bytes are exactly half the expert payload of its
  stage (a rank reads ITS experts' bytes, nothing else — the property
  that makes Mixtral-8x7B's 45 GB of int8 experts splittable 16 ways),
- the loader reads the checkpoint once (total attributed bytes ~= the
  stored payload; router/embed/norms memoized to one read despite the
  4-way mesh).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

INNER = r"""
import json, re, time
from pathlib import Path

import jax
assert len(jax.devices()) >= 4, jax.devices()

from cake_tpu.models import llama
from cake_tpu.models.config import tiny_moe
from cake_tpu.parallel.mesh import MeshPlan
from cake_tpu.tools.quantize_model import quantize_checkpoint
from cake_tpu.utils import sharded_load
from cake_tpu.utils.weights import save_llama_params

E = 4
cfg = tiny_moe(num_hidden_layers=8, num_local_experts=E, max_seq_len=32)
root = Path(r"{tmp}")
bf = root / "bf16"
params = llama.init_params(cfg, jax.random.PRNGKey(0))
save_llama_params(params, bf, cfg.num_hidden_layers)

q8 = root / "q8"
quantize_checkpoint(bf, q8, shard_bytes=1 << 18)  # several shard files
index = json.loads((q8 / "model.safetensors.index.json").read_text())
shard_files = sorted(set(index["weight_map"].values()))
assert len(shard_files) >= 2, shard_files
payload = index["metadata"]["total_size"]

# attribute reads: expert tensors bucket by (stage, ep-rank); everything
# else by stage / other
expert_re = re.compile(
    r"model\.layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.")
layer_re = re.compile(r"model\.layers\.(\d+)\.")
S, EPD = 2, 2
layers_per = cfg.num_hidden_layers // S
experts_per = E // EPD
expert_bytes = [[0] * EPD for _ in range(S)]
other = [0]

def account(name, nbytes):
    m = expert_re.match(name)
    if m:
        expert_bytes[int(m.group(1)) // layers_per][
            int(m.group(2)) // experts_per] += nbytes
        return
    other[0] += nbytes

orig1, orig2 = (sharded_load.CheckpointReader.read1d,
                sharded_load.CheckpointReader.read2d)

def read1d(self, name, sl=slice(None)):
    out = orig1(self, name, sl)
    account(name, out.nbytes)
    return out

def read2d(self, name, rows, cols, transpose):
    out = orig2(self, name, rows, cols, transpose)
    account(name, out.nbytes)
    return out

sharded_load.CheckpointReader.read1d = read1d
sharded_load.CheckpointReader.read2d = read2d

plan = MeshPlan.build(cfg, num_stages=S, ep=EPD,
                      devices=jax.devices()[: S * EPD])
t0 = time.perf_counter()
loaded = sharded_load.load_llama_params_on_mesh(
    q8, cfg, plan.mesh, quantize="int8")
for leaf in jax.tree.leaves(loaded):
    leaf.block_until_ready()
dt = time.perf_counter() - t0

tot = sum(sum(row) for row in expert_bytes)
# every (stage, ep-rank) cell reads exactly its 1/(S*EPD) of expert bytes
for s in range(S):
    for e in range(EPD):
        assert expert_bytes[s][e] == tot // (S * EPD), (
            s, e, expert_bytes, tot)
# read-once: attributed total ~= stored payload
grand = tot + other[0]
assert abs(grand - payload) / payload < 0.05, (grand, payload)

q = loaded["layers"]["w_gate"].q
assert q.shape[:2] == (cfg.num_hidden_layers, E) and str(q.dtype) == "int8"
print(json.dumps({
    "shards": len(shard_files),
    "payload_bytes": payload,
    "per_rank_expert_bytes": expert_bytes[0][0],
    "load_s": round(dt, 3),
}))
print("moe fileplane ok")
"""


def test_moe_multishard_q8_load_stage2_ep2(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=4"]
    )
    r = subprocess.run(
        [sys.executable, "-c", INNER.replace("{tmp}", str(tmp_path))],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    assert "moe fileplane ok" in r.stdout
    stats = json.loads(r.stdout.strip().splitlines()[-2])
    assert stats["shards"] >= 2
