"""Splitter round-trip: bundles load back and serve generation correctly."""

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.parallel.topology import Topology
from cake_tpu.tools.split_model import main as split_main, split_for_worker
from cake_tpu.utils.weights import load_llama_params, save_llama_params

CFG = tiny()


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("model")
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype="float32")
    save_llama_params(params, d)
    (d / "config.json").write_text(json.dumps(CFG.to_hf_dict()))
    return d


def _topo(tmp_path):
    t = Topology.from_dict({
        "w1": {"host": "10.0.0.1:10128", "layers": ["model.layers.0-1"]},
        "w2": {"host": "10.0.0.2:10128", "layers": ["model.layers.2-3"]},
    })
    p = tmp_path / "topology.yml"
    t.save(p)
    return t, p


def test_split_cli_all_workers(model_dir, tmp_path, capsys):
    _, topo_path = _topo(tmp_path)
    rc = split_main([
        "--model-path", str(model_dir),
        "--topology", str(topo_path),
        "--output", str(tmp_path / "bundles"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "w1:" in out and "w2:" in out
    for w in ("w1", "w2"):
        bundle = tmp_path / "bundles" / f"{w}-node"
        assert (bundle / "model" / "reduced.safetensors").exists()
        assert (bundle / "model" / "model.safetensors.index.json").exists()
        assert (bundle / "model" / "config.json").exists()
        assert (bundle / "topology.yml").exists()


def test_bundle_contains_only_own_layers(model_dir, tmp_path):
    topo, _ = _topo(tmp_path)
    out = split_for_worker(model_dir, tmp_path / "b", topo, topo["w1"])
    index = json.loads((out / "model.safetensors.index.json").read_text())
    names = set(index["weight_map"])
    assert all(n.startswith("model.layers.0.") or n.startswith("model.layers.1.")
               for n in names)
    assert not any("model.layers.2" in n for n in names)
    assert not any(n.startswith("model.embed") for n in names)  # head stays local


def test_bundle_loads_with_layer_range(model_dir, tmp_path):
    """A worker bundle must load through the normal weights loader and match
    the original tensors exactly."""
    topo, _ = _topo(tmp_path)
    out = split_for_worker(model_dir, tmp_path / "b", topo, topo["w2"])
    part = load_llama_params(
        out, CFG.num_hidden_layers, dtype="float32",
        layer_range=(2, 4), include_embed=False, include_head=False,
    )
    full = load_llama_params(model_dir, CFG.num_hidden_layers, dtype="float32")
    np.testing.assert_array_equal(
        np.asarray(part["layers"]["wq"]),
        np.asarray(full["layers"]["wq"][2:4]),
    )


def test_single_worker_flag(model_dir, tmp_path):
    _, topo_path = _topo(tmp_path)
    rc = split_main([
        "--model-path", str(model_dir),
        "--topology", str(topo_path),
        "--output", str(tmp_path / "one"),
        "--worker", "w2",
    ])
    assert rc == 0
    assert (tmp_path / "one" / "w2-node").exists()
    assert not (tmp_path / "one" / "w1-node").exists()


def test_single_node_topology_written(model_dir, tmp_path):
    topo, _ = _topo(tmp_path)
    split_for_worker(model_dir, tmp_path / "b", topo, topo["w1"])
    t = Topology.from_path(tmp_path / "b" / "w1-node" / "topology.yml")
    assert len(t) == 1
    assert t["w1"].host == "10.0.0.1:10128"
    assert t["w1"].layer_indices() == [0, 1]
