"""The runnable examples must stay runnable — they're the first thing a
user switching from the reference executes (MIGRATING.md / README)."""

import os
import runpy

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_serve_demo_runs(capsys):
    runpy.run_path(os.path.join(_ROOT, "examples", "serve_demo.py"),
                   run_name="__main__")
    out = capsys.readouterr().out
    assert out.count("stream ") == 4  # all four slots reported
    assert "stream 99:" in out  # the mid-run arrival was admitted
    assert "tokens/dispatch" in out
