"""The wedge-proof driver-artifact path (r5 verdict item 1): when the
live probe falls to CPU, bench.py's one JSON line must carry the freshest
TPU-stamped ledger rows and headline the metric of record. BENCH_r05.json
is built from exactly this logic, so it gets its own unit pins."""

import json

import bench


def _row(metric, value, stamp, platform="tpu"):
    return {"metric": metric, "value": value, "unit": "tokens/s",
            "vs_baseline": 0.5, "device": "TPU v5 lite",
            "platform": platform, "stamp": stamp}


def test_tpu_ledger_dedups_filters_and_sorts(tmp_path, monkeypatch):
    p = tmp_path / "ledger.jsonl"
    rows = [
        _row("decode_tokens_per_sec_llama_8b_int8_1chip", 80.0,
             "2026-07-30T01:00:00Z"),
        _row("decode_tokens_per_sec_llama_8b_int8_1chip", 84.8,
             "2026-07-31T07:18:03Z"),  # later line wins for the metric
        _row("ttft_p50_ms_llama_8b_int8_1chip_t256", 111.8,
             "2026-07-31T07:21:59Z"),
        _row("decode_tokens_per_sec_llama_tiny_bf16_1chip", 900.0,
             "2026-07-31T15:00:00Z", platform="cpu"),  # CPU rows excluded
        "not json at all",
    ]
    with open(p, "w") as f:
        for r in rows:
            f.write((r if isinstance(r, str) else json.dumps(r)) + "\n")
    monkeypatch.setattr(bench, "_ledger_path", lambda: str(p))

    led = bench._tpu_ledger()
    assert [r["metric"] for r in led] == [
        "ttft_p50_ms_llama_8b_int8_1chip_t256",
        "decode_tokens_per_sec_llama_8b_int8_1chip",
    ]  # newest first, one row per metric
    assert led[1]["value"] == 84.8  # the freshest landing, not the first
    assert all(r["platform"] == "tpu" for r in led)


def test_tpu_ledger_missing_file_is_empty(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_ledger_path",
                        lambda: str(tmp_path / "absent.jsonl"))
    assert bench._tpu_ledger() == []


def test_pick_headline_prefers_int8_single_stream():
    led = [
        _row("decode_tokens_per_sec_llama_8b_int4_1chip_b8", 418.5,
             "2026-07-31T07:30:55Z"),
        _row("decode_tokens_per_sec_llama_8b_int4_1chip", 51.0,
             "2026-07-31T07:19:47Z"),
        _row("decode_tokens_per_sec_llama_8b_int8_1chip", 84.8,
             "2026-07-31T07:18:03Z"),
    ]
    # int8 single-stream (the metric of record) beats fresher int4 rows
    assert bench._pick_headline(led)["value"] == 84.8
    # without an int8 row: any single-stream decode row beats serving rows
    assert bench._pick_headline(led[:2])["value"] == 51.0
    # no single-stream decode row at all: freshest wins (stable min)
    assert bench._pick_headline(led[:1])["value"] == 418.5
