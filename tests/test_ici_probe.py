"""ICI probe (tools/ici_probe): the machinery behind BASELINE.json's
"inter-layer ICI latency" metric — a timed ppermute ring over the same
stage axis the pipeline rides. On the CPU test mesh the numbers are
host-memcpy (labeled by device kind); the contract proven here is the
machinery: ring correctness, per-size records, JSON output."""

import json

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from cake_tpu.parallel.mesh import STAGE, make_mesh
from cake_tpu.tools.ici_probe import _build_ring, probe


def test_ring_permutes_payload_correctly():
    n, reps = 4, 3
    mesh = make_mesh(num_stages=n, devices=jax.devices()[:n])
    fn = _build_ring(mesh, n, reps)
    x = jax.numpy.arange(n * 2, dtype=jax.numpy.bfloat16)
    out = np.asarray(fn(x)).astype(np.float32)
    # each 2-element shard moved reps hops around the ring
    shards = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
    want = np.roll(shards, reps, axis=0).reshape(-1)
    np.testing.assert_array_equal(out, want)


def test_probe_emits_records(tmp_path, capsys):
    out = tmp_path / "ici.json"
    recs = probe(stages=4, reps=4, json_out=str(out))
    assert len(recs) == 4
    for r in recs:
        assert r["per_hop_us"] > 0 and r["n_stages"] == 4
        assert r["payload_bytes"] > 0
    assert json.loads(out.read_text()) == recs


def test_probe_refuses_single_device(monkeypatch, capsys):
    one = jax.devices()[:1]
    monkeypatch.setattr(jax, "devices", lambda: one)
    assert probe() == []
