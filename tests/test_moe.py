"""MoE routing/compute unit tests (no torch oracle needed — f64 numpy loop
is the reference math; HF golden parity lives in test_families.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cake_tpu.ops.moe import (
    GATHER_MAX_ROWS,
    _moe_dense,
    _moe_gather,
    moe_swiglu,
    router_topk,
)


def _fixtures(n=3, h=16, f=32, e=4, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    x = jax.random.normal(ks[0], (n, h))
    rw = jax.random.normal(ks[1], (h, e))
    wg = jax.random.normal(ks[2], (e, h, f)) / 4
    wu = jax.random.normal(ks[3], (e, h, f)) / 4
    wd = jax.random.normal(ks[4], (e, f, h)) / 6
    return x, rw, wg, wu, wd


def _oracle(x, rw, wg, wu, wd, k):
    """Per-token f64 loop: top-k by logit, softmax over selected, routed
    SwiGLU sum."""
    x64 = np.asarray(x, np.float64)
    logits = x64 @ np.asarray(rw, np.float64)
    out = np.zeros_like(x64)

    def silu(v):
        return v / (1 + np.exp(-v))

    for n in range(x64.shape[0]):
        top = np.argsort(-logits[n], kind="stable")[:k]
        w = np.exp(logits[n][top] - logits[n][top].max())
        w /= w.sum()
        for wgt, e in zip(w, top):
            hidden = silu(x64[n] @ np.asarray(wg[e], np.float64)) * (
                x64[n] @ np.asarray(wu[e], np.float64)
            )
            out[n] += wgt * (hidden @ np.asarray(wd[e], np.float64))
    return out


def test_router_combine_weights_normalized():
    x, rw, *_ = _fixtures()
    combine, w, idx = router_topk(x, rw, 2)
    np.testing.assert_allclose(np.asarray(combine.sum(-1)), 1.0, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-5)
    # combine's nonzeros sit exactly at the top-k indices
    nz = np.asarray(combine) > 0
    for n in range(x.shape[0]):
        assert set(np.nonzero(nz[n])[0]) == set(np.asarray(idx[n]))


def test_dense_and_gather_agree_with_oracle():
    x, rw, wg, wu, wd = _fixtures()
    combine, w, idx = router_topk(x, rw, 2)
    dense = _moe_dense(x, combine, wg, wu, wd)
    gather = _moe_gather(x, w, idx, wg, wu, wd)
    oracle = _oracle(x, rw, wg, wu, wd, 2)
    np.testing.assert_allclose(np.asarray(dense), oracle, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gather), oracle, rtol=2e-5, atol=2e-5)


def test_auto_strategy_crossover_consistency():
    """The same inputs produce the same outputs whichever side of the
    gather/dense crossover N lands on (pad the batch to push it across)."""
    x, rw, wg, wu, wd = _fixtures(n=2)
    small = moe_swiglu(x[None], rw, wg, wu, wd, 2)  # N*k=4 -> gather
    big_n = GATHER_MAX_ROWS  # N*k = 2*GATHER_MAX_ROWS -> dense
    xb = jnp.concatenate([x, jnp.zeros((big_n - 2, x.shape[1]), x.dtype)])
    big = moe_swiglu(xb[None], rw, wg, wu, wd, 2)
    np.testing.assert_allclose(np.asarray(small[0]), np.asarray(big[0, :2]),
                               rtol=2e-5, atol=2e-5)


def test_moe_swiglu_shapes_and_finite():
    x, rw, wg, wu, wd = _fixtures(n=12)  # N*k=24 -> dense path
    out = moe_swiglu(x.reshape(3, 4, -1), rw, wg, wu, wd, 2)
    assert out.shape == (3, 4, x.shape[-1])
    assert bool(jnp.isfinite(out).all())


def test_top1_routing():
    """Switch-style top-1: softmax over one logit = weight 1.0 on the
    argmax expert."""
    x, rw, wg, wu, wd = _fixtures()
    out = moe_swiglu(x[None], rw, wg, wu, wd, 1)
    oracle = _oracle(x, rw, wg, wu, wd, 1)
    np.testing.assert_allclose(np.asarray(out[0]), oracle, rtol=2e-5,
                               atol=2e-5)


@pytest.mark.parametrize("ep", [2, 4])
def test_expert_parallel_matches_single_device(ep):
    """Experts sharded over an ep mesh axis via shard_map: the psum'd
    combine must equal the unsharded op bit-for-bit in structure (same
    routing) and numerically."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from cake_tpu.parallel.mesh import shard_map

    x, rw, wg, wu, wd = _fixtures(n=4, e=4)
    devs = jax.devices()[:ep]
    mesh = Mesh(np.array(devs), ("ep",))
    spec_w = P("ep")  # expert axis sharded
    repl = P()

    def f(x, rw, wg, wu, wd):
        return moe_swiglu(x, rw, wg, wu, wd, 2, ep_axis="ep", ep_size=ep)

    sharded = shard_map(
        f, mesh=mesh,
        in_specs=(repl, repl, spec_w, spec_w, spec_w),
        out_specs=repl,
    )
    got = sharded(x[None], rw,
                  jax.device_put(wg, NamedSharding(mesh, spec_w)),
                  jax.device_put(wu, NamedSharding(mesh, spec_w)),
                  jax.device_put(wd, NamedSharding(mesh, spec_w)))
    want = moe_swiglu(x[None], rw, wg, wu, wd, 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# MoE over the mesh pipeline: the full generator surface with the expert
# axis sharded (stage x ep x tp), token-identical to the all-local stream.
# ---------------------------------------------------------------------------

from cake_tpu.models import llama  # noqa: E402
from cake_tpu.models.config import tiny_moe  # noqa: E402
from cake_tpu.ops.sampling import SamplerSettings  # noqa: E402
from cake_tpu.runtime.generator import LlamaGenerator  # noqa: E402
from cake_tpu.runtime.mesh_generator import MeshGenerator  # noqa: E402

MOE_CFG = tiny_moe(max_seq_len=64)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)


@pytest.fixture(scope="module")
def moe_params():
    return llama.init_params(MOE_CFG, jax.random.PRNGKey(5))


@pytest.mark.parametrize(
    "axes",
    [
        dict(ep=2),
        dict(ep=4),
        dict(num_stages=2, ep=2),
        dict(num_stages=2, ep=2, tp=2),
    ],
    ids=lambda a: "-".join(f"{k}{v}" for k, v in a.items()),
)
def test_moe_mesh_greedy_parity_with_local(moe_params, axes):
    settings = SamplerSettings(**GREEDY)
    ref = LlamaGenerator(MOE_CFG, moe_params, settings=settings)
    ref.set_prompt([5, 9, 2, 11])
    want = [ref.next_token(i).id for i in range(6)]

    g = MeshGenerator(MOE_CFG, moe_params, settings=settings, **axes)
    g.set_prompt([5, 9, 2, 11])
    assert [g.next_token(i).id for i in range(6)] == want


def test_ep_requires_moe_config():
    from cake_tpu.models.config import tiny
    from cake_tpu.parallel.mesh import MeshPlan

    with pytest.raises(ValueError, match="num_local_experts"):
        MeshPlan.build(tiny(), ep=2)
    with pytest.raises(ValueError, match="divisible"):
        MeshPlan.build(tiny_moe(), ep=3)


def test_moe_serving_batch_generator_parity(moe_params):
    """MoE serves multi-stream on an ep x stage mesh: every stream must
    reproduce its solo all-local run token-for-token (the BatchGenerator
    bar, test_batch_generator.py, now with routed experts under ep)."""
    from cake_tpu.runtime.batch_generator import BatchGenerator

    settings = SamplerSettings(**GREEDY)
    prompts = [[5, 9, 2, 11], [3, 1, 4, 1, 5], [7, 7, 2]]

    solo = []
    for p in prompts:
        g = LlamaGenerator(MOE_CFG, moe_params, settings=settings)
        g.set_prompt(p)
        solo.append([g.next_token(i).id for i in range(6)])

    bg = BatchGenerator(MOE_CFG, moe_params, settings=settings,
                        num_stages=2, ep=2, block_size=2)
    bg.set_prompts(prompts)
    outs = bg.generate(6)
    assert [list(o) for o in outs] == solo


def test_moe_int8_experts_match_dequantized_oracle():
    """moe_swiglu over int8 expert stacks equals the same op over the
    explicitly dequantized arrays bit-for-bit (both strategies)."""
    from cake_tpu.ops.quant import dequantize_linear, quantize_linear

    x, rw, wg, wu, wd = _fixtures(n=2)
    qg, qu, qd = (quantize_linear(w) for w in (wg, wu, wd))
    dg, du, dd = (dequantize_linear(q, jnp.float32) for q in (qg, qu, qd))
    got_g = moe_swiglu(x[None], rw, qg, qu, qd, 2)  # gather path (N*k=4)
    want_g = moe_swiglu(x[None], rw, dg, du, dd, 2)
    np.testing.assert_array_equal(np.asarray(got_g), np.asarray(want_g))
    xb = jnp.concatenate([x, jnp.zeros((8, x.shape[1]), x.dtype)])
    got_d = moe_swiglu(xb[None], rw, qg, qu, qd, 2)  # dense path
    want_d = moe_swiglu(xb[None], rw, dg, du, dd, 2)
    np.testing.assert_array_equal(np.asarray(got_d), np.asarray(want_d))


def test_moe_int8_mesh_parity_with_local():
    """int8 expert stacks shard over ep (q takes the weight spec, scale
    [L, E, F] drops the in axis) and the mesh stream matches all-local."""
    from cake_tpu.ops.quant import quantize_params

    qparams = quantize_params(
        llama.init_params(MOE_CFG, jax.random.PRNGKey(5)), bits=8
    )
    settings = SamplerSettings(**GREEDY)
    ref = LlamaGenerator(MOE_CFG, qparams, settings=settings)
    ref.set_prompt([5, 9, 2, 11])
    want = [ref.next_token(i).id for i in range(6)]

    g = MeshGenerator(MOE_CFG, qparams, settings=settings, num_stages=2,
                      ep=2)
    g.set_prompt([5, 9, 2, 11])
    assert [g.next_token(i).id for i in range(6)] == want


def test_moe_int8_init_params():
    from cake_tpu.models import llama as L
    from cake_tpu.ops.quant import QuantizedLinear

    p = L.init_params_int8(MOE_CFG, jax.random.PRNGKey(0))
    assert isinstance(p["layers"]["w_gate"], QuantizedLinear)
    assert p["layers"]["w_gate"].q.ndim == 4  # [L, E, H, F]
    assert p["layers"]["router"].dtype == MOE_CFG.jax_dtype
    with pytest.raises(NotImplementedError, match="int4"):
        L.init_params_int4(MOE_CFG, jax.random.PRNGKey(0))


def test_mixtral_hbm_budget():
    """Budget arithmetic prices MoE expert stacks (x num_experts / ep) —
    the planning plane behind serving Mixtral-8x7B on a v5e-16."""
    from cake_tpu.models.config import mixtral_8x7b
    from cake_tpu.utils.memory import hbm_budget

    g = 1 << 30
    m = mixtral_8x7b(max_seq_len=4096)
    one = hbm_budget(m, quant="int8")
    sharded = hbm_budget(m, num_stages=4, ep=4, quant="int8")
    # experts dominate: 16-way expert-bytes split must shrink the total
    # close to 1/16 of the expert bytes (+ replicated embed/router floor)
    assert one["total"] / g > 40  # ~45 GB of int8 experts on one chip
    assert sharded["total"] / g < 4
    # ep shards ONLY the expert bytes: the ep=1 vs ep=4 layer-byte delta
    # must equal exactly (1 - 1/ep) of the expert bytes — a regression
    # that divided attention/norm bytes by ep would break this equality
    b = hbm_budget(m, num_stages=4, ep=1, quant="int8")
    e = m.num_local_experts
    expert_bytes = (
        m.num_hidden_layers / 4  # layers per stage
        * e
        * (3 * m.hidden_size * m.intermediate_size * 1  # int8 q bytes
           + (2 * m.intermediate_size + m.hidden_size) * 4)  # f32 scales
    )
    assert b["layers"] - sharded["layers"] == pytest.approx(
        expert_bytes * (1 - 1 / 4), rel=1e-6
    )


def test_moe_distributed_worker_parity(moe_params):
    """The cross-host master/worker runtime serves MoE layers unchanged —
    expert stacks slice by layer range like any stacked weight, and the
    TCP-shipped activations reproduce the all-local stream exactly."""
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedGenerator, build_runners
    from cake_tpu.runtime.worker import Worker

    def loader(lo, hi):
        return jax.tree.map(lambda a: a[lo:hi], moe_params["layers"])

    w = Worker(
        "w", MOE_CFG,
        Topology.from_dict({"w": {"layers": ["model.layers.2-3"]}}),
        loader, address="127.0.0.1:0", max_seq=MOE_CFG.max_seq_len,
    )
    w.serve_in_background()
    try:
        topo = Topology.from_dict({
            "w": {"host": f"127.0.0.1:{w.port}",
                  "layers": ["model.layers.2-3"]},
        })
        settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
        runners = build_runners(MOE_CFG, topo, loader)
        head = {k: moe_params[k] for k in ("embed", "norm_f", "lm_head")}
        g = DistributedGenerator(MOE_CFG, head, runners, settings=settings)
        g.set_prompt([5, 9, 2])
        got = [g.next_token(i).id for i in range(6)]
        ref = LlamaGenerator(MOE_CFG, moe_params, settings=settings)
        ref.set_prompt([5, 9, 2])
        assert got == [ref.next_token(i).id for i in range(6)]
        g.close()
    finally:
        w.shutdown()


def test_moe_mesh_speculation_parity(moe_params):
    """Speculation over the ep mesh: the verification program (one pass
    over stage x ep) must reproduce the plain MoE stream bit for bit —
    the greedy exactness contract of speculative decoding."""
    from cake_tpu.runtime.speculative import MeshSpeculativeGenerator

    settings = SamplerSettings(**GREEDY)
    # repetitive prompt: n-gram proposals actually fire
    prompt = [5, 9, 2, 5, 9, 2, 5, 9, 2]
    ref = LlamaGenerator(MOE_CFG, moe_params, settings=settings)
    ref.set_prompt(prompt)
    want = [ref.next_token(i).id for i in range(8)]

    g = MeshSpeculativeGenerator(MOE_CFG, moe_params, settings=settings,
                                 num_stages=2, ep=2, spec_k=4)
    g.set_prompt(prompt)
    assert [g.next_token(i).id for i in range(8)] == want
    assert g.dispatches < 8  # speculation actually engaged


def test_moe_serving_int8kv_interleaved_parity(moe_params):
    """MoE x int8 KV cache x interleaved-microbatch decode (batch divides
    stages, so BatchGenerator auto-selects the GPipe-streamed schedule):
    every stream still reproduces its solo bf16-KV-free run... rather,
    its solo int8-KV oracle, token for token."""
    from cake_tpu.runtime.batch_generator import BatchGenerator

    settings = SamplerSettings(**GREEDY)
    prompts = [[5, 9, 2, 11], [3, 1, 4, 1], [7, 7, 2], [9, 8, 7, 6]]

    solo = []
    for p in prompts:
        g = LlamaGenerator(MOE_CFG, moe_params, settings=settings,
                          kv_quant="int8")
        g.set_prompt(p)
        solo.append([g.next_token(i).id for i in range(6)])

    bg = BatchGenerator(MOE_CFG, moe_params, settings=settings,
                        num_stages=2, ep=2, block_size=2, kv_quant="int8")
    bg.set_prompts(prompts)
    assert bg._interleave  # 4 streams over 2 stages: GPipe schedule on
    outs = bg.generate(6)
    assert [list(o) for o in outs] == solo
