"""MoE routing/compute unit tests (no torch oracle needed — f64 numpy loop
is the reference math; HF golden parity lives in test_families.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cake_tpu.ops.moe import (
    GATHER_MAX_ROWS,
    _moe_dense,
    _moe_gather,
    moe_swiglu,
    router_topk,
)


def _fixtures(n=3, h=16, f=32, e=4, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    x = jax.random.normal(ks[0], (n, h))
    rw = jax.random.normal(ks[1], (h, e))
    wg = jax.random.normal(ks[2], (e, h, f)) / 4
    wu = jax.random.normal(ks[3], (e, h, f)) / 4
    wd = jax.random.normal(ks[4], (e, f, h)) / 6
    return x, rw, wg, wu, wd


def _oracle(x, rw, wg, wu, wd, k):
    """Per-token f64 loop: top-k by logit, softmax over selected, routed
    SwiGLU sum."""
    x64 = np.asarray(x, np.float64)
    logits = x64 @ np.asarray(rw, np.float64)
    out = np.zeros_like(x64)

    def silu(v):
        return v / (1 + np.exp(-v))

    for n in range(x64.shape[0]):
        top = np.argsort(-logits[n], kind="stable")[:k]
        w = np.exp(logits[n][top] - logits[n][top].max())
        w /= w.sum()
        for wgt, e in zip(w, top):
            hidden = silu(x64[n] @ np.asarray(wg[e], np.float64)) * (
                x64[n] @ np.asarray(wu[e], np.float64)
            )
            out[n] += wgt * (hidden @ np.asarray(wd[e], np.float64))
    return out


def test_router_combine_weights_normalized():
    x, rw, *_ = _fixtures()
    combine, w, idx = router_topk(x, rw, 2)
    np.testing.assert_allclose(np.asarray(combine.sum(-1)), 1.0, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-5)
    # combine's nonzeros sit exactly at the top-k indices
    nz = np.asarray(combine) > 0
    for n in range(x.shape[0]):
        assert set(np.nonzero(nz[n])[0]) == set(np.asarray(idx[n]))


def test_dense_and_gather_agree_with_oracle():
    x, rw, wg, wu, wd = _fixtures()
    combine, w, idx = router_topk(x, rw, 2)
    dense = _moe_dense(x, combine, wg, wu, wd)
    gather = _moe_gather(x, w, idx, wg, wu, wd)
    oracle = _oracle(x, rw, wg, wu, wd, 2)
    np.testing.assert_allclose(np.asarray(dense), oracle, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gather), oracle, rtol=2e-5, atol=2e-5)


def test_auto_strategy_crossover_consistency():
    """The same inputs produce the same outputs whichever side of the
    gather/dense crossover N lands on (pad the batch to push it across)."""
    x, rw, wg, wu, wd = _fixtures(n=2)
    small = moe_swiglu(x[None], rw, wg, wu, wd, 2)  # N*k=4 -> gather
    big_n = GATHER_MAX_ROWS  # N*k = 2*GATHER_MAX_ROWS -> dense
    xb = jnp.concatenate([x, jnp.zeros((big_n - 2, x.shape[1]), x.dtype)])
    big = moe_swiglu(xb[None], rw, wg, wu, wd, 2)
    np.testing.assert_allclose(np.asarray(small[0]), np.asarray(big[0, :2]),
                               rtol=2e-5, atol=2e-5)


def test_moe_swiglu_shapes_and_finite():
    x, rw, wg, wu, wd = _fixtures(n=12)  # N*k=24 -> dense path
    out = moe_swiglu(x.reshape(3, 4, -1), rw, wg, wu, wd, 2)
    assert out.shape == (3, 4, x.shape[-1])
    assert bool(jnp.isfinite(out).all())


def test_top1_routing():
    """Switch-style top-1: softmax over one logit = weight 1.0 on the
    argmax expert."""
    x, rw, wg, wu, wd = _fixtures()
    out = moe_swiglu(x[None], rw, wg, wu, wd, 1)
    oracle = _oracle(x, rw, wg, wu, wd, 1)
    np.testing.assert_allclose(np.asarray(out[0]), oracle, rtol=2e-5,
                               atol=2e-5)


@pytest.mark.parametrize("ep", [2, 4])
def test_expert_parallel_matches_single_device(ep):
    """Experts sharded over an ep mesh axis via shard_map: the psum'd
    combine must equal the unsharded op bit-for-bit in structure (same
    routing) and numerically."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    shard_map = jax.shard_map

    x, rw, wg, wu, wd = _fixtures(n=4, e=4)
    devs = jax.devices()[:ep]
    mesh = Mesh(np.array(devs), ("ep",))
    spec_w = P("ep")  # expert axis sharded
    repl = P()

    def f(x, rw, wg, wu, wd):
        return moe_swiglu(x, rw, wg, wu, wd, 2, ep_axis="ep", ep_size=ep)

    sharded = shard_map(
        f, mesh=mesh,
        in_specs=(repl, repl, spec_w, spec_w, spec_w),
        out_specs=repl,
    )
    got = sharded(x[None], rw,
                  jax.device_put(wg, NamedSharding(mesh, spec_w)),
                  jax.device_put(wu, NamedSharding(mesh, spec_w)),
                  jax.device_put(wd, NamedSharding(mesh, spec_w)))
    want = moe_swiglu(x[None], rw, wg, wu, wd, 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# MoE over the mesh pipeline: the full generator surface with the expert
# axis sharded (stage x ep x tp), token-identical to the all-local stream.
# ---------------------------------------------------------------------------

from cake_tpu.models import llama  # noqa: E402
from cake_tpu.models.config import tiny_moe  # noqa: E402
from cake_tpu.ops.sampling import SamplerSettings  # noqa: E402
from cake_tpu.runtime.generator import LlamaGenerator  # noqa: E402
from cake_tpu.runtime.mesh_generator import MeshGenerator  # noqa: E402

MOE_CFG = tiny_moe(max_seq_len=64)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)


@pytest.fixture(scope="module")
def moe_params():
    return llama.init_params(MOE_CFG, jax.random.PRNGKey(5))


@pytest.mark.parametrize(
    "axes",
    [
        dict(ep=2),
        dict(ep=4),
        dict(num_stages=2, ep=2),
        dict(num_stages=2, ep=2, tp=2),
    ],
    ids=lambda a: "-".join(f"{k}{v}" for k, v in a.items()),
)
def test_moe_mesh_greedy_parity_with_local(moe_params, axes):
    settings = SamplerSettings(**GREEDY)
    ref = LlamaGenerator(MOE_CFG, moe_params, settings=settings)
    ref.set_prompt([5, 9, 2, 11])
    want = [ref.next_token(i).id for i in range(6)]

    g = MeshGenerator(MOE_CFG, moe_params, settings=settings, **axes)
    g.set_prompt([5, 9, 2, 11])
    assert [g.next_token(i).id for i in range(6)] == want


def test_ep_requires_moe_config():
    from cake_tpu.models.config import tiny
    from cake_tpu.parallel.mesh import MeshPlan

    with pytest.raises(ValueError, match="num_local_experts"):
        MeshPlan.build(tiny(), ep=2)
    with pytest.raises(ValueError, match="divisible"):
        MeshPlan.build(tiny_moe(), ep=3)
