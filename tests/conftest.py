"""Test harness: run everything on a virtual 8-device CPU mesh.

SURVEY.md §4: the reference has no tests; the strategy here is built from
scratch — tiny random-weight configs, golden parity against HF transformers,
and multi-device sharding tests on `--xla_force_host_platform_device_count=8`
CPU devices (no pod required).
"""

import os

# The test suite always runs on a virtual 8-device CPU mesh; TPU execution is
# exercised by bench.py. The XLA_FLAGS env must be set before the CPU backend
# initializes; the platform itself is forced via jax.config (a sitecustomize
# on this box eagerly registers the TPU plugin and freezes the env-derived
# default before conftest runs, so the env var alone is not enough).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_threefry_partitionable", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from tier-1 (`-m 'not slow'`) and the smoke targets",
    )


# The suites whose execution exercises the engine-thread boundary run
# with the CK-THREAD runtime twin armed (runtime/threadcheck): the
# scheduler stamps its engine thread and every annotated engine/pool
# mutator asserts domain membership — so the static thread-domain model
# (cake_tpu/analysis/thread_domains.py) is validated against real
# execution, not just the AST.
_THREAD_STRICT_SUITES = ("test_serve", "test_kvpool", "test_disagg",
                         "test_gateway", "test_sp_serving")


@pytest.fixture(autouse=True)
def _thread_strict_twin(request):
    if request.module.__name__.rpartition(".")[2] in _THREAD_STRICT_SUITES:
        from cake_tpu.runtime import threadcheck

        prev = threadcheck.set_strict(True)
        yield
        threadcheck.set_strict(prev)
    else:
        yield


@pytest.fixture(scope="session")
def tiny_config():
    from cake_tpu.models.config import tiny

    return tiny()


@pytest.fixture(scope="session")
def tiny_params(tiny_config):
    from cake_tpu.models.llama import init_params

    return init_params(tiny_config, jax.random.PRNGKey(0))
