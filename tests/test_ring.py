"""Ring attention / sequence-parallel decode parity vs the XLA oracle.

The oracle is `cake_tpu.ops.attention._attend_xla` (reference-math full-score
attention). Ring/SP paths must reproduce it up to f32 reduction order on the
virtual 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cake_tpu.ops import ring
from cake_tpu.parallel.mesh import shard_map
from cake_tpu.ops.attention import _attend_xla


def _qkv(key, b=1, heads=4, kv_heads=2, t=16, s=16, d=8, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, heads, t, d), dtype)
    k = jax.random.normal(kk, (b, kv_heads, s, d), dtype)
    v = jax.random.normal(kv, (b, kv_heads, s, d), dtype)
    return q, k, v


def test_stats_match_oracle_full_block():
    q, k, v = _qkv(jax.random.PRNGKey(0))
    o, m, l = ring.attend_stats(q, k, v, q_off=0, k_off=0)
    got = ring.finalize_stats(o, m, l, q.dtype)
    want = _attend_xla(q, k, v, pos=0)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_stats_merge_over_chunks():
    q, k, v = _qkv(jax.random.PRNGKey(1), t=8, s=32)
    want = _attend_xla(q, k, v, pos=24)  # q positions 24..31, all 32 keys live
    chunk = 8
    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full(q.shape[:3], ring.NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3], jnp.float32)
    for c0 in range(0, 32, chunk):
        o_p, m_p, l_p = ring.attend_stats(
            q, k[:, :, c0:c0 + chunk], v[:, :, c0:c0 + chunk],
            q_off=24, k_off=c0,
        )
        o, m, l = ring.merge_stats(o, m, l, o_p, m_p, l_p)
    got = ring.finalize_stats(o, m, l, q.dtype)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_fully_masked_rows_are_finite():
    q, k, v = _qkv(jax.random.PRNGKey(2), t=4, s=8)
    # k_off far beyond the causal frontier: nothing attends.
    o, m, l = ring.attend_stats(q, k, v, q_off=0, k_off=1000)
    out = ring.finalize_stats(o, m, l, q.dtype)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out), 0.0)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_attention_parity(sp):
    t_total = 32
    t_l = t_total // sp
    q, k, v = _qkv(jax.random.PRNGKey(3), t=t_total, s=t_total)
    want = _attend_xla(q, k, v, pos=0)

    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    spec = P(None, None, "sp", None)

    def f(q, k, v):
        my = jax.lax.axis_index("sp")
        return ring.ring_attention(
            q, k, v, "sp", sp, q_off=my * t_l,
        )

    got = jax.jit(
        shard_map(
            f, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)


def test_ring_attention_restores_kv_layout():
    """After the full rotation, each shard's KV block is back home: verify by
    returning k from inside the shard_map and comparing to the input."""
    sp, t_l = 4, 8
    q, k, v = _qkv(jax.random.PRNGKey(4), t=sp * t_l, s=sp * t_l)
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    spec = P(None, None, "sp", None)

    def f(q, k, v):
        my = jax.lax.axis_index("sp")
        out = ring.ring_attention(q, k, v, "sp", sp, q_off=my * t_l)
        return out, k

    _, k_after = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(spec,) * 3,
                      out_specs=(spec, spec), check_vma=False)
    )(q, k, v)
    np.testing.assert_array_equal(np.asarray(k_after), np.asarray(k))


@pytest.mark.parametrize("pos", [0, 5, 31])
def test_sp_decode_parity(pos):
    sp = 4
    s_total = 32
    s_l = s_total // sp
    q, k, v = _qkv(jax.random.PRNGKey(5), t=1, s=s_total)
    want = _attend_xla(q, k, v, pos=pos)

    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    kv_spec = P(None, None, "sp", None)

    def f(q, k, v):
        my = jax.lax.axis_index("sp")
        return ring.sp_decode_attend(q, k, v, pos, "sp", my * s_l)

    got = jax.jit(
        shard_map(
            f, mesh=mesh,
            in_specs=(P(None), kv_spec, kv_spec),
            out_specs=P(None),
            check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)


@pytest.mark.parametrize("pos", [0, 7, 8, 30])
def test_sp_cache_write_owner_only(pos):
    sp, s_l = 4, 8
    b, kh, d = 1, 2, 4
    k_cache = jnp.zeros((b, kh, sp * s_l, d))
    v_cache = jnp.zeros_like(k_cache)
    k_new = jnp.ones((b, kh, 1, d))
    v_new = jnp.full((b, kh, 1, d), 2.0)

    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    kv_spec = P(None, None, "sp", None)

    def f(kc, vc, kn, vn):
        my = jax.lax.axis_index("sp")
        return ring.sp_cache_write(kc, vc, kn, vn, pos, my * s_l)

    kc, vc = jax.jit(
        shard_map(
            f, mesh=mesh,
            in_specs=(kv_spec, kv_spec, P(None), P(None)),
            out_specs=(kv_spec, kv_spec),
            check_vma=False,
        )
    )(k_cache, v_cache, k_new, v_new)
    kc = np.asarray(kc)
    vc = np.asarray(vc)
    assert (kc[:, :, pos] == 1.0).all()
    assert (vc[:, :, pos] == 2.0).all()
    mask = np.ones(sp * s_l, bool)
    mask[pos] = False
    assert (kc[:, :, mask] == 0.0).all()
    assert (vc[:, :, mask] == 0.0).all()
