"""Worker status surface: live JSON over HTTP.

The reference's worker host is a SwiftUI app rendering the worker's
name/device/layers/state (`cake-ios-worker-app/Cake
Worker/ContentView.swift:28-56`). A TPU-VM worker is headless, so the
equivalent is `Worker.start_status_server` — identity + serving counters
as JSON any browser/curl can read (CLI `--status-port`)."""

import json
import urllib.request

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime.master import DistributedGenerator, build_runners
from cake_tpu.runtime.worker import Worker

CFG = tiny(max_seq_len=32)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(3))


def _loader(params):
    return lambda lo, hi: jax.tree.map(lambda a: a[lo:hi], params["layers"])


def _get(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=10) as r:
        assert r.headers["Content-Type"] == "application/json"
        return json.loads(r.read())


def test_status_page_reports_identity_and_counters(params):
    topo = Topology.from_dict({"w1": {"layers": ["model.layers.0-3"]}})
    w = Worker("w1", CFG, topo, _loader(params), address="127.0.0.1:0",
               max_seq=CFG.max_seq_len)
    w.serve_in_background()
    port = w.start_status_server(0)
    try:
        st = _get(port)
        assert st["name"] == "w1"
        assert st["layer_runs"] == [[0, CFG.num_hidden_layers]]
        assert st["ops_total"] == 0 and st["connections_total"] == 0
        assert st["rss_bytes"] > 0 and st["uptime_s"] >= 0

        # drive real ops through the wire and watch the counters move
        wire_topo = Topology.from_dict({
            "w1": {"host": f"127.0.0.1:{w.port}",
                   "layers": ["model.layers.0-3"]},
        })
        runners = build_runners(CFG, wire_topo, _loader(params))
        g = DistributedGenerator(
            CFG, {k: params[k] for k in ("embed", "norm_f", "lm_head")},
            runners,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        )
        g.set_prompt([3, 5, 7])
        for i in range(3):
            g.next_token(i)
        st = _get(port)
        assert st["connections_total"] >= 1
        assert st["ops_total"] > 0
        assert st["bytes_in"] > 0 and st["bytes_out"] > 0
    finally:
        w.shutdown()
    # shutdown also stops the HTTP server
    with pytest.raises(Exception):
        _get(port)
