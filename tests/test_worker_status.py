"""Worker status surface: live JSON over HTTP.

The reference's worker host is a SwiftUI app rendering the worker's
name/device/layers/state (`cake-ios-worker-app/Cake
Worker/ContentView.swift:28-56`). A TPU-VM worker is headless, so the
equivalent is `Worker.start_status_server` — identity + serving counters
as JSON any browser/curl can read (CLI `--status-port`)."""

import json
import urllib.request

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime.master import DistributedGenerator, build_runners
from cake_tpu.runtime.worker import Worker

CFG = tiny(max_seq_len=32)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(3))


def _loader(params):
    return lambda lo, hi: jax.tree.map(lambda a: a[lo:hi], params["layers"])


def _get(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=10) as r:
        assert r.headers["Content-Type"] == "application/json"
        return json.loads(r.read())


def test_status_page_reports_identity_and_counters(params):
    topo = Topology.from_dict({"w1": {"layers": ["model.layers.0-3"]}})
    w = Worker("w1", CFG, topo, _loader(params), address="127.0.0.1:0",
               max_seq=CFG.max_seq_len)
    w.serve_in_background()
    port = w.start_status_server(0)
    try:
        st = _get(port)
        assert st["name"] == "w1"
        assert st["layer_runs"] == [[0, CFG.num_hidden_layers]]
        assert st["ops_total"] == 0 and st["connections_total"] == 0
        assert st["rss_bytes"] > 0 and st["uptime_s"] >= 0

        # drive real ops through the wire and watch the counters move
        wire_topo = Topology.from_dict({
            "w1": {"host": f"127.0.0.1:{w.port}",
                   "layers": ["model.layers.0-3"]},
        })
        runners = build_runners(CFG, wire_topo, _loader(params))
        g = DistributedGenerator(
            CFG, {k: params[k] for k in ("embed", "norm_f", "lm_head")},
            runners,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        )
        g.set_prompt([3, 5, 7])
        for i in range(3):
            g.next_token(i)
        st = _get(port)
        assert st["connections_total"] >= 1
        assert st["ops_total"] > 0
        assert st["bytes_in"] > 0 and st["bytes_out"] > 0
    finally:
        w.shutdown()
    # shutdown also stops the HTTP server
    with pytest.raises(Exception):
        _get(port)


def test_watch_renders_live_worker_and_marks_dead_host(params):
    """r5: the watch tool (the interactive view over the status surface —
    the reference worker GUI's ticking table) renders a live worker's row
    from its real status page and shows unreachable hosts as DOWN
    without dying."""
    from cake_tpu.tools import watch

    topo = Topology.from_dict({"w1": {"layers": ["model.layers.0-3"]}})
    w = Worker("w1", CFG, topo, _loader(params), address="127.0.0.1:0",
               max_seq=CFG.max_seq_len)
    port = w.start_status_server(0)
    try:
        live = f"127.0.0.1:{port}"
        dead = "127.0.0.1:1"  # nothing listens on port 1
        snaps = [watch.fetch_status(live), watch.fetch_status(dead)]
        assert snaps[0]["name"] == "w1" and "error" in snaps[1]
        prev: dict = {}
        frame = watch.render([live, dead], snaps, prev, dt=0.0)
        assert "w1@" in frame and "0-3" in frame
        assert "DOWN" in frame
        # second frame: counter deltas come from prev (zeros here, but the
        # delta path executes)
        snaps2 = [watch.fetch_status(live), watch.fetch_status(dead)]
        frame2 = watch.render([live, dead], snaps2, prev, dt=1.0)
        assert "w1@" in frame2

        # --once exit code: nonzero while a host is down, zero when all up
        assert watch.main([live, dead, "--once"]) == 1
        assert watch.main([live, "--once"]) == 0
    finally:
        w.shutdown()


def test_watch_hosts_from_topology(tmp_path):
    from cake_tpu.tools import watch

    topo = Topology.from_dict({
        "a": {"host": "10.0.0.1:10128", "layers": ["model.layers.0-1"]},
        "b": {"host": "10.0.0.2:10129", "layers": ["model.layers.2-3"]},
        "local": {"layers": ["model.layers.4-5"]},  # no host -> skipped
    })
    p = tmp_path / "topo.yaml"
    topo.save(p)
    assert watch.hosts_from_topology(str(p), 8090) == [
        "10.0.0.1:8090", "10.0.0.2:8090",
    ]
