"""Int8 KV cache (kvcache.QuantizedKV): quantize-on-write, dequant-in-attend.

The serving-side long-context lever the reference's f16-only cache
(cache.rs:106-135) has no answer to: half the cache HBM, so batch x window
roughly doubles on a fixed budget (utils/memory.hbm_budget prices it).
Held to greedy-token parity with the bf16 cache at tiny scale across the
local, mesh, and serving execution paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.kvcache import (
    QuantizedKV,
    dequant_kv,
    init_cache,
    quant_kv,
    update_layer,
)
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.generator import LlamaGenerator

CFG = tiny(max_seq_len=64)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(9))


def test_quant_roundtrip_error_bounded():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 16), jnp.bfloat16)
    deq = dequant_kv(quant_kv(x), jnp.float32)
    err = jnp.max(jnp.abs(deq - x.astype(jnp.float32)))
    # symmetric int8: error <= absmax/127 per (token, head) channel
    assert float(err) <= float(jnp.max(jnp.abs(x.astype(jnp.float32)))) / 127 + 1e-6


def test_init_cache_int8_halves_bytes():
    bf = init_cache(CFG, batch=2, max_seq=64)
    q8 = init_cache(CFG, batch=2, max_seq=64, quant="int8")
    bf_bytes = sum(x.nbytes for x in jax.tree.leaves(bf))
    q8_bytes = sum(x.nbytes for x in jax.tree.leaves(q8))
    assert isinstance(q8.k, QuantizedKV)
    assert q8_bytes < 0.75 * bf_bytes  # int8 + scales vs bf16


def test_update_layer_int8_slots_and_gate():
    """Writes land at the right slots with per-slot scales; the SPMD write
    gate predicates both the int8 bytes and the scales."""
    s, t = 16, 3
    cfg = tiny(max_seq_len=s)
    kh, d = cfg.num_key_value_heads, cfg.head_dim
    cache = init_cache(cfg, batch=1, max_seq=s, quant="int8")
    k_layer, v_layer = jax.tree.map(lambda x: x[0], (cache.k, cache.v))
    k_new = jax.random.normal(jax.random.PRNGKey(1), (1, kh, t, d), jnp.bfloat16)
    v_new = jax.random.normal(jax.random.PRNGKey(2), (1, kh, t, d), jnp.bfloat16)
    k2, v2 = update_layer(k_layer, v_layer, k_new, v_new, jnp.int32(5))
    deq = dequant_kv(k2, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(deq[:, :, 5:5 + t]), np.asarray(k_new, np.float32),
        atol=0.05,
    )
    assert np.asarray(deq[:, :, :5]).max() == 0  # untouched slots stay zero
    # gated off: nothing lands
    k3, _ = update_layer(k_layer, v_layer, k_new, v_new, jnp.int32(5),
                         gate=jnp.asarray(False))
    assert np.asarray(dequant_kv(k3, jnp.float32)).max() == 0


def _greedy(gen, prompt, n):
    gen.set_prompt(prompt)
    return [gen.next_token(i).id for i in range(n)]


def test_local_generator_int8_kv_matches_bf16(params):
    settings = SamplerSettings(**GREEDY)
    ref = _greedy(LlamaGenerator(CFG, params, settings=settings), [5, 9, 2], 8)
    got = _greedy(
        LlamaGenerator(CFG, params, settings=settings, kv_quant="int8"),
        [5, 9, 2], 8,
    )
    assert got == ref


def test_local_generator_int8_kv_block_decode(params):
    settings = SamplerSettings(**GREEDY)
    ref = _greedy(
        LlamaGenerator(CFG, params, settings=settings, kv_quant="int8"),
        [3, 1, 4], 8,
    )
    got = _greedy(
        LlamaGenerator(CFG, params, settings=settings, kv_quant="int8",
                       block_size=4),
        [3, 1, 4], 8,
    )
    assert got == ref


def test_mesh_generator_int8_kv(params):
    from cake_tpu.runtime.mesh_generator import MeshGenerator

    settings = SamplerSettings(**GREEDY)
    ref = _greedy(LlamaGenerator(CFG, params, settings=settings), [7, 7, 2], 6)
    gen = MeshGenerator(CFG, params, settings=settings, num_stages=2, tp=2,
                        kv_quant="int8")
    assert _greedy(gen, [7, 7, 2], 6) == ref


@pytest.mark.parametrize("stages", [1, 2])
def test_int8_kv_composes_with_sequence_parallelism(params, stages):
    """The long-context plane and the quantized cache compose: sp=2 ring
    prefill + distributed decode over int8 KV matches the single-device
    int8-KV oracle token-for-token (the sp paths quantize-on-write and the
    ring attends the same round-tripped values the cache holds)."""
    from cake_tpu.runtime.mesh_generator import MeshGenerator

    settings = SamplerSettings(**GREEDY)
    prompt = [5, 9, 2, 11, 3, 8]
    want = _greedy(LlamaGenerator(CFG, params, settings=settings,
                                  kv_quant="int8"), prompt, 8)
    g = MeshGenerator(CFG, params, settings=settings, num_stages=stages,
                      sp=2, kv_quant="int8")
    assert _greedy(g, prompt, 8) == want


def test_int8_kv_sp_long_prompt_chunked_write(params):
    """A prompt long enough to exercise the chunked sp cache write (bucket
    < window) with quantized halves riding the all-gather."""
    from cake_tpu.runtime.mesh_generator import MeshGenerator

    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompt = list(range(2, 2 + 20))  # buckets to 32 < max_seq 64
    want = _greedy(LlamaGenerator(CFG, params, settings=settings,
                                  kv_quant="int8"), prompt, 6)
    g = MeshGenerator(CFG, params, settings=settings, sp=2,
                      kv_quant="int8")
    assert _greedy(g, prompt, 6) == want


def test_batch_generator_int8_kv_serving_and_admit(params):
    """The serving plane with int8 KV: every concurrent greedy stream is
    bit-identical to its own solo int8 run (the per-stream independence
    contract — int8-vs-bf16 drift compounds over long runs, so cross-dtype
    parity is only held at short range by the local-path test above), and
    admit() splices a quantized KV row correctly."""
    from cake_tpu.runtime.batch_generator import BatchGenerator

    settings = SamplerSettings(**GREEDY)
    prompts = [[5, 9, 2, 11], [3, 1, 4, 1, 5, 9], [7, 7, 2]]

    g = BatchGenerator(CFG, params, settings=settings, dp=1,
                       block_size=4, kv_quant="int8")
    g.set_prompts(prompts)
    got = g.generate(8)
    for i, prompt in enumerate(prompts):
        solo = BatchGenerator(CFG, params, settings=settings, dp=1,
                              block_size=4, kv_quant="int8")
        solo.set_prompts([prompt], stream_ids=[i])
        assert got[i] == solo.generate(8)[0]

    # finish stream 2 artificially, then admit a new prompt into its slot
    g.streams[2].done = True
    slot, first = g.admit([2, 8, 1], stream_id=9)
    assert slot == 2
    outs = [g.step() for _ in range(4)]
    admitted = [first.id] + [r[2].id for r in outs if r[2] is not None]
    solo = BatchGenerator(CFG, params, settings=settings, dp=1,
                          block_size=4, kv_quant="int8")
    solo.set_prompts([[2, 8, 1]], stream_ids=[9])
    want = solo.generate(len(admitted))[0][: len(admitted)]
    assert admitted == want


def test_hbm_budget_prices_int8_kv():
    from cake_tpu.utils.memory import hbm_budget

    cfg = tiny(max_seq_len=4096)
    bf = hbm_budget(cfg, batch=32, max_seq=4096)["kv_cache"]
    q8 = hbm_budget(cfg, batch=32, max_seq=4096,
                    cache_bytes_per_el=1)["kv_cache"]
    assert q8 < 0.75 * bf
    # scales are priced: strictly more than the bare int8 bytes
    assert q8 > bf / 2 * 0.99
