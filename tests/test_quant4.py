"""Packed int4 weight quantization: pack/unpack round-trip, kernel parity,
model quality, loader equivalence, and sharded execution.

The int4 tier is a capability the TPU build adds beyond the reference's
f16/bf16 dtype plane (`cake/mod.rs:56-62`): decode is HBM-bandwidth-bound,
so halving the int8 bytes again roughly doubles the single-stream roofline
(BASELINE.md). The adjacent-pair packing convention (ops/quant.py) is
load-bearing for tensor parallelism — tested explicitly here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops import quant
from cake_tpu.ops.kvcache import init_cache
from cake_tpu.ops.pallas.quant import quant4_matmul_pallas
from cake_tpu.ops.quant import (
    Quantized4Linear,
    dense,
    dequantize_linear4,
    pack_int4,
    quantize_linear4,
    quantize_linear4_np,
    quantize_params,
    unpack_int4,
)


def test_pack_unpack_round_trip():
    rng = np.random.default_rng(0)
    q = rng.integers(-7, 8, size=(16, 8), dtype=np.int8)
    packed = pack_int4(jnp.asarray(q))
    assert packed.shape == (8, 8) and packed.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(unpack_int4(packed)), q)


def test_pack_adjacent_pair_layout():
    """Byte i holds rows 2i (low nibble) and 2i+1 (high) — the layout that
    makes contiguous packed-row ranges contiguous original-row ranges."""
    q = jnp.asarray([[1], [-2], [3], [-4]], jnp.int8)  # K=4, N=1
    p = np.asarray(pack_int4(q))[:, 0]
    # byte 0 = rows 0,1; byte 1 = rows 2,3
    assert p[0] == np.int8((1 & 0xF) | (np.int8(-2) << 4))
    assert p[1] == np.int8((3 & 0xF) | (np.int8(-4) << 4))
    # shard the packed rows: rows [1, 2) must decode to original rows [2, 4)
    shard = pack_int4(q)[1:2]
    np.testing.assert_array_equal(
        np.asarray(unpack_int4(shard)), np.asarray(q)[2:4]
    )


def test_pack_odd_k_rejected():
    with pytest.raises(ValueError, match="even"):
        pack_int4(jnp.zeros((3, 4), jnp.int8))


def test_quantize4_round_trip():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
    ql = quantize_linear4(w)
    assert ql.qp.shape == (32, 32) and ql.qp.dtype == jnp.int8
    assert ql.scale.shape == (32,)
    back = dequantize_linear4(ql, jnp.float32)
    # max error bounded by half a quantization step per channel
    step = np.asarray(ql.scale)
    err = np.abs(np.asarray(back) - np.asarray(w))
    assert (err <= 0.5 * step[None, :] + 1e-7).all()


def test_quantize4_np_matches_jax():
    w = np.random.default_rng(1).standard_normal((48, 16)).astype(np.float32)
    ql = quantize_linear4(jnp.asarray(w))
    qp, scale = quantize_linear4_np(w)
    np.testing.assert_array_equal(qp, np.asarray(ql.qp))
    np.testing.assert_allclose(scale, np.asarray(ql.scale), rtol=1e-6)


def test_quantize4_stacked_scale_axes():
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 16, 8), jnp.float32)
    ql = quantize_linear4(w)
    assert ql.qp.shape == (3, 8, 8)
    assert ql.scale.shape == (3, 8)


def test_quant4_matmul_xla_matches_dequant():
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (64, 32), jnp.float32)
    ql = quantize_linear4(w)
    ref = x @ dequantize_linear4(ql, jnp.float32)
    out = quant.quant4_matmul_xla(x, ql.qp, ql.scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_quant4_matmul_pallas_matches_xla():
    key = jax.random.PRNGKey(3)
    x = jax.random.normal(key, (8, 64), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (64, 32), jnp.float32)
    ql = quantize_linear4(w)
    ref = quant.quant4_matmul_xla(x, ql.qp, ql.scale)
    out = quant4_matmul_pallas(x, ql.qp, ql.scale, block_m=4, block_n=8,
                               block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_dense_dispatch_int4():
    x = jnp.ones((2, 8), jnp.float32)
    w = jnp.ones((8, 4), jnp.float32)
    ql = quantize_linear4(w)
    assert quant.out_features(ql) == 4
    np.testing.assert_allclose(np.asarray(dense(x, ql)), 8.0, rtol=1e-2)


def test_pinned_impl_applies_to_int4():
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 256), jnp.bfloat16)
    w = quantize_linear4(
        jax.random.normal(jax.random.PRNGKey(2), (256, 256), jnp.float32))
    y_xla = quant.quant4_matmul(x, w.qp, w.scale, impl="xla")
    with quant.pinned_impl("xla"):
        np.testing.assert_array_equal(
            quant.quant4_matmul(x, w.qp, w.scale), y_xla)
    assert quant.pinned() is None


@pytest.fixture(scope="module")
def cfg():
    return tiny(max_seq_len=32)


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(cfg, jax.random.PRNGKey(0))


def test_quantize_params_bits4(cfg, params):
    qparams = quantize_params(params, bits=4)
    assert isinstance(qparams["layers"]["wq"], Quantized4Linear)
    assert isinstance(qparams["lm_head"], Quantized4Linear)
    assert not isinstance(qparams["layers"]["attn_norm"], Quantized4Linear)
    with pytest.raises(ValueError, match="bits"):
        quantize_params(params, bits=5)


def _logits_cosine(cfg, params, qparams) -> float:
    ids = [3, 1, 4, 1, 5, 9, 2, 6]
    tokens = jnp.asarray([ids], jnp.int32)
    logits_f, _ = llama.forward(
        params, tokens, init_cache(cfg, 1, cfg.max_seq_len), 0, cfg
    )
    logits_q, _ = llama.forward(
        qparams, tokens, init_cache(cfg, 1, cfg.max_seq_len), 0, cfg
    )
    a = np.asarray(logits_f[0], np.float64)
    b = np.asarray(logits_q[0], np.float64)
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))


def test_int4_model_logits_close(cfg, params):
    """Per-channel int4 is the bandwidth tier: coarse but usable."""
    cos = _logits_cosine(cfg, params, quantize_params(params, bits=4))
    assert cos > 0.9, f"cosine similarity {cos}"


def test_int4_grouped_recovers_accuracy():
    """Group-wise scales are the accuracy tier. On iid-gaussian weights
    grouping buys nothing (absmax is uniform across rows — measured, the
    model-level cosine is ~identical), so this exercises the case grouping
    exists for: heterogeneous row magnitudes (real checkpoints' outlier
    structure). Per-channel absmax is then dominated by the loud rows and
    quiet rows quantize to ~0; per-group scales isolate them."""
    rng = np.random.default_rng(3)
    w = rng.standard_normal((64, 32)).astype(np.float32)
    w[:16] *= 50.0  # one loud 16-row band, three quiet ones

    def rel_err(ql):
        back = np.asarray(dequantize_linear4(ql, jnp.float32))
        return np.abs(back - w)[16:].max() / np.abs(w[16:]).max()

    err_pc = rel_err(quantize_linear4(jnp.asarray(w)))
    err_g = rel_err(quantize_linear4(jnp.asarray(w), group_size=16))
    assert err_pc > 0.5  # quiet rows destroyed by the loud band's scale
    assert err_g < 0.1, f"grouped rel err {err_g}"
    # model-level: grouped int4 stays in the per-channel fidelity envelope
    # on iid weights (sanity that grouping never hurts)
    assert err_g < err_pc


def test_quantize4_grouped_round_trip():
    w = jax.random.normal(jax.random.PRNGKey(5), (64, 32), jnp.float32)
    ql = quantize_linear4(w, group_size=16)
    assert ql.qp.shape == (32, 32)
    assert ql.scale.shape == (4, 32)
    assert ql.group_size == 16
    back = dequantize_linear4(ql, jnp.float32)
    step = np.asarray(ql.scale)  # [4, 32] — per (group, channel) step
    err = np.abs(np.asarray(back) - np.asarray(w)).reshape(4, 16, 32)
    assert (err <= 0.5 * step[:, None, :] + 1e-7).all()
    # numpy variant agrees
    qp_np, s_np = quantize_linear4_np(np.asarray(w), group_size=16)
    np.testing.assert_array_equal(qp_np, np.asarray(ql.qp))
    np.testing.assert_allclose(s_np, np.asarray(ql.scale), rtol=1e-6)


def test_quant4_grouped_matmul_paths_agree():
    key = jax.random.PRNGKey(6)
    x = jax.random.normal(key, (8, 64), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (64, 32), jnp.float32)
    ql = quantize_linear4(w, group_size=16)
    ref = x @ dequantize_linear4(ql, jnp.float32)
    y_xla = quant.quant4_matmul_xla(x, ql.qp, ql.scale)
    np.testing.assert_allclose(np.asarray(y_xla), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    y_pl = quant4_matmul_pallas(x, ql.qp, ql.scale, block_m=4, block_n=8,
                                block_k=4, interpret=True)
    np.testing.assert_allclose(np.asarray(y_pl), np.asarray(y_xla),
                               rtol=1e-5, atol=1e-5)
    # dense dispatches on the scale rank alone
    y_d = dense(x, ql)
    np.testing.assert_allclose(np.asarray(y_d), np.asarray(y_xla),
                               rtol=1e-5, atol=1e-5)


def test_quant4_grouped_matmul_bf16_activations():
    """The grouped fallback runs with bf16 activations on CPU (the CPU
    batched-dot thunk rejects bf16 x bf16 -> f32, so the fallback computes
    in f32) — the dtype every real CLI flow uses."""
    key = jax.random.PRNGKey(12)
    x = jax.random.normal(key, (2, 64), jnp.bfloat16)
    w = jax.random.normal(jax.random.fold_in(key, 1), (64, 32), jnp.float32)
    ql = quantize_linear4(w, group_size=16)
    y = jax.jit(quant.quant4_matmul_xla)(x, ql.qp, ql.scale)
    assert y.dtype == jnp.bfloat16
    ref = (x.astype(jnp.float32)
           @ dequantize_linear4(ql, jnp.float32)).astype(jnp.bfloat16)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(ref, np.float32),
        rtol=0.05, atol=0.05)


def test_quantize4_group_size_validation():
    w = jnp.zeros((64, 8), jnp.float32)
    with pytest.raises(ValueError, match="group_size"):
        quantize_linear4(w, group_size=24)  # does not divide 64
    with pytest.raises(ValueError, match="group_size"):
        quantize_linear4(w, group_size=3)  # odd
    with pytest.raises(ValueError, match="group_size"):
        quantize_params({"lm_head": w}, bits=8, group_size=16)


def test_int4_generation_runs(cfg, params):
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    g = LlamaGenerator(cfg, quantize_params(params, bits=4),
                       settings=SamplerSettings(temperature=0.0))
    g.set_prompt([3, 1, 4])
    ids = [g.next_token(i).id for i in range(6)]
    assert len(ids) == 6
    assert all(0 <= t < cfg.vocab_size for t in ids)


def test_int4_block_decode_matches_single(cfg, params):
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    qp = quantize_params(params, bits=4)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    a = LlamaGenerator(cfg, qp, settings=settings)
    a.set_prompt([5, 9, 2])
    single = [a.next_token(i).id for i in range(9)]
    b = LlamaGenerator(cfg, qp, settings=settings, block_size=4)
    b.set_prompt([5, 9, 2])
    assert [b.next_token(i).id for i in range(9)] == single


def test_init_params_int4_structure(cfg):
    p = llama.init_params_int4(cfg, jax.random.PRNGKey(7))
    assert isinstance(p["layers"]["wq"], Quantized4Linear)
    assert isinstance(p["lm_head"], Quantized4Linear)
    h = cfg.hidden_size
    assert p["layers"]["wq"].qp.shape[1] == h // 2
    # generation works end-to-end from the packed init
    logits, _ = llama.forward(
        p, jnp.asarray([[1, 2, 3]], jnp.int32),
        init_cache(cfg, 1, cfg.max_seq_len), 0, cfg,
    )
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_int4_sharded_pipeline_matches_local(cfg, params):
    """int4 params shard over (stage, tp) — the adjacent-pair packing makes
    the row-parallel (in-axis) tp shards decode the right values — and the
    one-program mesh decode agrees with the unsharded int4 model."""
    from cake_tpu.ops import sampling
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.parallel.mesh import MeshPlan, shard_cache, shard_params
    from cake_tpu.parallel.pipeline import build_sharded_decode

    qparams = quantize_params(params, bits=4)
    plan = MeshPlan.build(cfg, num_stages=2, tp=2)
    sp = shard_params(qparams, plan.mesh)
    settings = SamplerSettings(temperature=0.0)
    decode = build_sharded_decode(cfg, settings, plan, params_like=qparams)
    cache = shard_cache(init_cache(cfg, 1, cfg.max_seq_len), plan.mesh)
    history, hist_slot = sampling.init_history(settings.repeat_last_n)
    tok, cache, history, hist_slot = decode(
        sp, jnp.asarray([5], jnp.int32), cache, jnp.int32(0),
        jax.random.PRNGKey(0), history[None, :], hist_slot,
    )
    logits_ref, _ = llama.forward(
        qparams, jnp.asarray([[5]], jnp.int32),
        init_cache(cfg, 1, cfg.max_seq_len), 0, cfg,
    )
    assert int(tok[0]) == int(jnp.argmax(logits_ref[0]))


def test_int4_grouped_sharded_pipeline_matches_local(cfg, params):
    """Grouped-scale int4 params shard over (stage, tp): the group axis
    shards with the in axis (mesh.param_specs), and the mesh decode agrees
    with the unsharded grouped model."""
    from cake_tpu.ops import sampling
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.parallel.mesh import MeshPlan, shard_cache, shard_params
    from cake_tpu.parallel.pipeline import build_sharded_decode

    qparams = quantize_params(params, bits=4, group_size=16)
    plan = MeshPlan.build(cfg, num_stages=2, tp=2)
    sp = shard_params(qparams, plan.mesh)
    settings = SamplerSettings(temperature=0.0)
    decode = build_sharded_decode(cfg, settings, plan, params_like=qparams)
    cache = shard_cache(init_cache(cfg, 1, cfg.max_seq_len), plan.mesh)
    history, hist_slot = sampling.init_history(settings.repeat_last_n)
    tok, cache, history, hist_slot = decode(
        sp, jnp.asarray([5], jnp.int32), cache, jnp.int32(0),
        jax.random.PRNGKey(0), history[None, :], hist_slot,
    )
    logits_ref, _ = llama.forward(
        qparams, jnp.asarray([[5]], jnp.int32),
        init_cache(cfg, 1, cfg.max_seq_len), 0, cfg,
    )
    assert int(tok[0]) == int(jnp.argmax(logits_ref[0]))


def test_head_chunk_grouped_scale_slices_vocab_axis():
    """_head_chunk on a grouped-int4 lm_head slices the vocab (last) scale
    axis, not the group axis — each stage's chunk decodes exactly like the
    matching column slice of the full head."""
    from cake_tpu.parallel.pipeline import _head_chunk

    w = jax.random.normal(jax.random.PRNGKey(8), (32, 64), jnp.float32)
    ql = quantize_linear4(w, group_size=8)  # scale [4, 64]
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 32), jnp.float32)
    full = np.asarray(dense(x, ql))
    S = 4
    for stage in range(S):
        chunk = _head_chunk(ql, stage, S)
        assert chunk.scale.shape == (4, 64 // S)
        np.testing.assert_allclose(
            np.asarray(dense(x, chunk)),
            full[:, stage * (64 // S):(stage + 1) * (64 // S)],
            rtol=1e-5, atol=1e-5,
        )


def test_int4_tp_shard_values_match_slice(cfg, params):
    """The sharded qp's per-device row-parallel blocks are exactly the pack
    of that shard's original-row slice (the property the packing layout
    exists for)."""
    from cake_tpu.parallel.mesh import MeshPlan, shard_params

    qparams = quantize_params(params, bits=4)
    plan = MeshPlan.build(cfg, num_stages=1, tp=2)
    sharded = shard_params(qparams, plan.mesh)
    full = np.asarray(qparams["layers"]["w_down"].qp)
    k2 = full.shape[1]
    for shard in sharded["layers"]["w_down"].qp.addressable_shards:
        a = shard.index[1].indices(k2)[0]
        b = shard.index[1].indices(k2)[1]
        np.testing.assert_array_equal(np.asarray(shard.data), full[:, a:b])


def test_int4_quantize_during_load_matches_posthoc(cfg, params, tmp_path):
    from cake_tpu.utils.weights import load_llama_params, save_llama_params

    save_llama_params(params, tmp_path)
    loaded_q = load_llama_params(
        tmp_path, cfg.num_hidden_layers, dtype="float32", quantize="int4"
    )
    posthoc = quantize_params(
        load_llama_params(tmp_path, cfg.num_hidden_layers, dtype="float32"),
        bits=4,
    )
    for name in ("wq", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(loaded_q["layers"][name].qp),
            np.asarray(posthoc["layers"][name].qp),
        )
        np.testing.assert_allclose(
            np.asarray(loaded_q["layers"][name].scale),
            np.asarray(posthoc["layers"][name].scale), rtol=1e-6,
        )
    np.testing.assert_array_equal(
        np.asarray(loaded_q["lm_head"].qp), np.asarray(posthoc["lm_head"].qp)
    )


def test_int4_mesh_load_matches_host_load(cfg, params, tmp_path):
    """Direct-to-mesh int4 load (packed-row sharding) is bitwise equal to
    host-load + shard (the loader's contract)."""
    from cake_tpu.parallel.mesh import MeshPlan, shard_params
    from cake_tpu.utils.sharded_load import load_llama_params_on_mesh
    from cake_tpu.utils.weights import load_llama_params, save_llama_params

    save_llama_params(params, tmp_path)
    plan = MeshPlan.build(cfg, num_stages=2, tp=2)
    mesh_q = load_llama_params_on_mesh(
        tmp_path, cfg, plan.mesh, quantize="int4",
    )
    host_q = shard_params(
        load_llama_params(tmp_path, cfg.num_hidden_layers,
                          dtype=cfg.dtype, quantize="int4"),
        plan.mesh,
    )
    for name in ("wq", "wo", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(mesh_q["layers"][name].qp),
            np.asarray(host_q["layers"][name].qp),
        )
        np.testing.assert_allclose(
            np.asarray(mesh_q["layers"][name].scale),
            np.asarray(host_q["layers"][name].scale), rtol=1e-6,
        )
    np.testing.assert_array_equal(
        np.asarray(mesh_q["lm_head"].qp), np.asarray(host_q["lm_head"].qp)
    )


def test_int4_prequantized_checkpoint_round_trip(cfg, params, tmp_path):
    """quantize_model --bits 4 writes .q4 tensors; loading the pre-quantized
    checkpoint equals quantize-on-load from the bf16 original."""
    from cake_tpu.tools.quantize_model import quantize_checkpoint
    from cake_tpu.utils.weights import load_llama_params, save_llama_params

    src = tmp_path / "src"
    dst = tmp_path / "q4"
    save_llama_params(params, src)
    quantize_checkpoint(src, dst, bits=4)
    pre = load_llama_params(dst, cfg.num_hidden_layers, dtype=cfg.dtype,
                            quantize="int4")
    onload = load_llama_params(src, cfg.num_hidden_layers, dtype=cfg.dtype,
                               quantize="int4")
    for name in ("wq", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(pre["layers"][name].qp),
            np.asarray(onload["layers"][name].qp),
        )
    np.testing.assert_array_equal(
        np.asarray(pre["lm_head"].qp), np.asarray(onload["lm_head"].qp)
    )
    # tier mismatch is rejected, not silently mis-decoded
    with pytest.raises(ValueError, match="int4"):
        load_llama_params(dst, cfg.num_hidden_layers, quantize="int8")


def test_parse_quant_spec():
    from cake_tpu.ops.quant import parse_quant_spec

    assert parse_quant_spec(None) == (None, None)
    assert parse_quant_spec("int8") == ("int8", None)
    assert parse_quant_spec("int4") == ("int4", None)
    assert parse_quant_spec("int4:g128") == ("int4", 128)
    with pytest.raises(ValueError, match="quantize spec"):
        parse_quant_spec("int2")
    with pytest.raises(ValueError, match="quantize spec"):
        parse_quant_spec("int8:g64")
    with pytest.raises(ValueError, match="quantize spec"):
        parse_quant_spec("int4:g0")  # \\d+ matches 0; must not pass


def test_int4_grouped_on_load_matches_posthoc(cfg, params, tmp_path):
    """quantize='int4:gN' on the host loader equals quantize_params with
    the same group size — the grouped tier is reachable from a plain
    checkpoint with one flag."""
    from cake_tpu.utils.weights import load_llama_params, save_llama_params

    save_llama_params(params, tmp_path)
    loaded = load_llama_params(
        tmp_path, cfg.num_hidden_layers, dtype="float32",
        quantize="int4:g16",
    )
    posthoc = quantize_params(
        load_llama_params(tmp_path, cfg.num_hidden_layers, dtype="float32"),
        bits=4, group_size=16,
    )
    assert loaded["layers"]["wq"].group_size == 16
    for name in ("wq", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(loaded["layers"][name].qp),
            np.asarray(posthoc["layers"][name].qp),
        )
        np.testing.assert_allclose(
            np.asarray(loaded["layers"][name].scale),
            np.asarray(posthoc["layers"][name].scale), rtol=1e-6,
        )


def test_int4_grouped_prequantized_checkpoint(cfg, params, tmp_path):
    """quantize_model --bits 4 --group-size writes grouped .q4 scales; both
    loaders read them back (grouping detected from the stored scale shape),
    and the direct-to-mesh load equals host-load + shard."""
    from cake_tpu.parallel.mesh import MeshPlan, shard_params
    from cake_tpu.tools.quantize_model import quantize_checkpoint
    from cake_tpu.utils.sharded_load import load_llama_params_on_mesh
    from cake_tpu.utils.weights import load_llama_params, save_llama_params

    src = tmp_path / "src"
    dst = tmp_path / "q4g"
    save_llama_params(params, src)
    quantize_checkpoint(src, dst, bits=4, group_size=16)
    pre = load_llama_params(dst, cfg.num_hidden_layers, dtype=cfg.dtype,
                            quantize="int4")
    assert pre["layers"]["wq"].group_size == 16
    onload = load_llama_params(src, cfg.num_hidden_layers, dtype=cfg.dtype,
                               quantize="int4:g16")
    np.testing.assert_array_equal(
        np.asarray(pre["layers"]["w_down"].qp),
        np.asarray(onload["layers"]["w_down"].qp),
    )
    np.testing.assert_allclose(
        np.asarray(pre["layers"]["w_down"].scale),
        np.asarray(onload["layers"]["w_down"].scale), rtol=1e-6,
    )
    plan = MeshPlan.build(cfg, num_stages=2, tp=2)
    mesh_q = load_llama_params_on_mesh(dst, cfg, plan.mesh, quantize="int4")
    host_q = shard_params(pre, plan.mesh)
    for name in ("wq", "wo", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(mesh_q["layers"][name].qp),
            np.asarray(host_q["layers"][name].qp),
        )
        np.testing.assert_allclose(
            np.asarray(mesh_q["layers"][name].scale),
            np.asarray(host_q["layers"][name].scale), rtol=1e-6,
        )
    np.testing.assert_array_equal(
        np.asarray(mesh_q["lm_head"].qp), np.asarray(host_q["lm_head"].qp))
    np.testing.assert_allclose(
        np.asarray(mesh_q["lm_head"].scale),
        np.asarray(host_q["lm_head"].scale), rtol=1e-6)


def test_int4_grouped_tied_head_loaders_agree(cfg, params, tmp_path):
    """A tied lm_head on a grouped pre-quantized checkpoint is quantized
    at the checkpoint's DETECTED group size by both loaders — host and
    direct-to-mesh heads are bit-equal (the loaders' equality contract)."""
    from cake_tpu.parallel.mesh import MeshPlan
    from cake_tpu.tools.quantize_model import quantize_checkpoint
    from cake_tpu.utils.sharded_load import load_llama_params_on_mesh
    from cake_tpu.utils.weights import load_llama_params, save_llama_params

    src = tmp_path / "src"
    dst = tmp_path / "q4g"
    save_llama_params(params, src)
    quantize_checkpoint(src, dst, bits=4, group_size=16)
    host = load_llama_params(dst, cfg.num_hidden_layers, dtype=cfg.dtype,
                             quantize="int4", tie_word_embeddings=True)
    # tied head fell back to on-the-fly quantize at the stored G, not
    # per-channel: grouped scale rank
    assert host["lm_head"].scale.ndim == 2
    assert host["lm_head"].group_size == 16
    plan = MeshPlan.build(cfg, num_stages=1, tp=2)
    mesh = load_llama_params_on_mesh(dst, cfg, plan.mesh, quantize="int4",
                                     tie_word_embeddings=True)
    np.testing.assert_array_equal(
        np.asarray(mesh["lm_head"].qp), np.asarray(host["lm_head"].qp))
    np.testing.assert_allclose(
        np.asarray(mesh["lm_head"].scale),
        np.asarray(host["lm_head"].scale), rtol=1e-6)


def test_int4_grouped_spec_mismatch_rejected_on_host(cfg, params, tmp_path):
    """Asking the host loader for g8 on a g16 checkpoint errors instead of
    silently dropping the request (parity with the sharded loader)."""
    from cake_tpu.tools.quantize_model import quantize_checkpoint
    from cake_tpu.utils.weights import load_llama_params, save_llama_params

    src = tmp_path / "src"
    dst = tmp_path / "q4g"
    save_llama_params(params, src)
    quantize_checkpoint(src, dst, bits=4, group_size=16)
    with pytest.raises(ValueError, match="group_size=16"):
        load_llama_params(dst, cfg.num_hidden_layers, quantize="int4:g8")


def test_int4_grouped_mesh_onload_rejected(cfg, params, tmp_path):
    """On-the-fly grouped quantize on the direct-to-mesh path points at the
    offline tool instead of silently degrading the tier."""
    from cake_tpu.parallel.mesh import MeshPlan
    from cake_tpu.utils.sharded_load import load_llama_params_on_mesh
    from cake_tpu.utils.weights import save_llama_params

    save_llama_params(params, tmp_path)
    plan = MeshPlan.build(cfg, num_stages=1, tp=1)
    with pytest.raises(ValueError, match="quantize_model"):
        load_llama_params_on_mesh(tmp_path, cfg, plan.mesh,
                                  quantize="int4:g16")


def test_int4_mesh_spec_vs_perchannel_checkpoint_rejected(cfg, params,
                                                         tmp_path):
    """Mesh loader: asking g16 of a PER-CHANNEL .q4 checkpoint errors
    (parity with the host loader) instead of silently loading coarse."""
    from cake_tpu.parallel.mesh import MeshPlan
    from cake_tpu.tools.quantize_model import quantize_checkpoint
    from cake_tpu.utils.sharded_load import load_llama_params_on_mesh
    from cake_tpu.utils.weights import save_llama_params

    src = tmp_path / "src"
    dst = tmp_path / "q4pc"
    save_llama_params(params, src)
    quantize_checkpoint(src, dst, bits=4)  # per-channel
    plan = MeshPlan.build(cfg, num_stages=1, tp=1)
    with pytest.raises(ValueError, match="per-channel"):
        load_llama_params_on_mesh(dst, cfg, plan.mesh, quantize="int4:g16")


def test_hbm_budget_prices_grouped_scales():
    """Grouped int4 scale bytes scale with in_dim/group — a near-limit
    config must see them (the planning arithmetic of BASELINE.md)."""
    from cake_tpu.models.config import LlamaConfig
    from cake_tpu.utils.memory import hbm_budget

    c = LlamaConfig(
        vocab_size=1024, hidden_size=256, intermediate_size=512,
        num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
        max_seq_len=128,
    )
    pc = hbm_budget(c, quant="int4")["total"]
    g = hbm_budget(c, quant="int4:g64")["total"]
    g_small = hbm_budget(c, quant="int4:g16")["total"]
    assert g > pc  # in_dim/64 scales per channel > 1 per channel
    assert g_small > g  # smaller groups, more scales


def test_int4_gate_guards_sublane_k_blocks(monkeypatch):
    """On a (simulated) compiled-TPU dispatch, grouped int4 whose K block
    would be sub-lane (g2 < 128) must fall back to XLA — the pin contract
    says pallas must never be chosen where it cannot lower."""
    from cake_tpu.ops import pallas as pk
    from cake_tpu.ops.pallas import quant as pq

    monkeypatch.setattr(pk, "kernels_enabled", lambda: True)
    monkeypatch.setattr(pk, "interpret_default", lambda: False)

    def boom(*a, **k):
        raise AssertionError("pallas kernel chosen for sub-lane K block")

    monkeypatch.setattr(pq, "quant4_matmul_pallas", boom)
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (256, 256), jnp.float32)
    ql = quantize_linear4(w, group_size=128)  # g2 = 64: not tileable
    with quant.pinned_impl("pallas"):
        y = quant.quant4_matmul(x, ql.qp, ql.scale)
    np.testing.assert_allclose(
        np.asarray(y),
        np.asarray(quant.quant4_matmul_xla(x, ql.qp, ql.scale)),
        rtol=1e-6)
    # per-channel at the same shapes IS tileable and would pick pallas
    ql_pc = quantize_linear4(w)
    with pytest.raises(AssertionError, match="sub-lane"):
        with quant.pinned_impl("pallas"):
            quant.quant4_matmul(x, ql_pc.qp, ql_pc.scale)


def test_int4_weights_compose_with_int8_kv(cfg):
    """int4 weights x int8 KV cache: both quantization planes in one
    serving instance, token streams identical to the bf16-KV int4 oracle
    within the int8-KV rounding envelope (here: greedy, same argmax)."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    c = tiny(max_seq_len=64, eos_token_id=-1)
    qparams = quantize_params(
        llama.init_params(c, jax.random.PRNGKey(4)), bits=4)

    def run(kv_quant):
        gen = BatchGenerator(c, qparams, kv_quant=kv_quant,
                             settings=SamplerSettings(temperature=0.0))
        gen.set_prompts([[5, 9, 2], [3, 3, 1]])
        out = []
        for _ in range(5):
            out.append([int(t.id) for t in gen.step()])
        return out

    bf16_kv = run(None)
    int8_kv = run("int8")
    assert len(int8_kv) == 5 and all(len(r) == 2 for r in int8_kv)
    # greedy streams agree on this tiny config (int8-KV rounding is below
    # the argmax margin here; regression-guards the composition wiring)
    assert int8_kv == bf16_kv


def test_int4_serving_batch_generator(cfg):
    """BatchGenerator serves int4 params (pin machinery included)."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    c = tiny(max_seq_len=64, eos_token_id=-1)
    qparams = quantize_params(
        llama.init_params(c, jax.random.PRNGKey(4)), bits=4)
    gen = BatchGenerator(c, qparams,
                         settings=SamplerSettings(temperature=0.0))
    gen.set_prompts([[5, 9, 2], [3, 3, 1]])
    assert gen._params_quantized  # int4 counts as quantized for pinning
    out = []
    for _ in range(4):
        row = gen.step()
        out.append([None if t is None else int(t.id) for t in row])
    assert all(len(r) == 2 for r in out)


def test_int16_unpack_variant_matches_int32():
    """The kernel's `unpack` width knob (tools/int4_sweep.py's variant
    axis) must not change the math — int16 sign-extension of a nibble is
    exact."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cake_tpu.ops.pallas.quant import quant4_matmul_pallas
    from cake_tpu.ops.quant import quantize_linear4

    key = jax.random.PRNGKey(3)
    x = jax.random.normal(key, (4, 256), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (256, 512),
                          jnp.float32)
    for gs in (None, 64):
        q4 = quantize_linear4(w, group_size=gs)
        a = quant4_matmul_pallas(x, q4.qp, q4.scale, unpack="int32",
                                 interpret=True)
        b = quant4_matmul_pallas(x, q4.qp, q4.scale, unpack="int16",
                                 interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
