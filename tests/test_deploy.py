"""Deploy tool (tools/deploy.py): the reference's rsync deploy plane
(`Makefile:29-39` sync_bahamut/sync_blade) generalized to every
host-addressed worker in a topology, dry-run by default."""

import subprocess
import sys

import pytest

from cake_tpu.parallel.topology import Topology
from cake_tpu.tools.deploy import _host_port, plan_commands

TOPO = Topology.from_dict({
    "alpha": {"host": "10.0.0.1:10128",
              "layers": ["model.layers.0-15"]},
    "beta": {"host": "10.0.0.2:9000",
             "layers": ["model.layers.16-31"]},
    "mesh_only": {"device": 0, "layers": ["model.layers.0-31"]},
})


def test_host_port_parsing():
    assert _host_port(TOPO.nodes["alpha"]) == ("10.0.0.1", 10128)
    assert _host_port(TOPO.nodes["beta"]) == ("10.0.0.2", 9000)


def test_plan_covers_each_host_with_code_and_bundle():
    cmds = plan_commands(TOPO, "/repo", "/bundles", "/opt/cake-tpu",
                         "/opt/cake-data")
    # 2 host nodes x (code rsync + bundle rsync); mesh-only node skipped
    assert len(cmds) == 4
    code_a, bundle_a, code_b, bundle_b = cmds
    assert code_a[0] == "rsync" and code_a[-1] == "10.0.0.1:/opt/cake-tpu/"
    assert any(x == "--exclude=.git" for x in code_a)
    assert bundle_a[-2:] == ["/bundles/alpha-node/",
                             "10.0.0.1:/opt/cake-data/alpha-node/"]
    assert bundle_b[-2:] == ["/bundles/beta-node/",
                             "10.0.0.2:/opt/cake-data/beta-node/"]


def test_plan_start_builds_worker_command_on_node_port():
    cmds = plan_commands(TOPO, "/repo", "/bundles", "/opt/cake-tpu",
                         "/opt/cake-data", start=True, ssh_user="ops")
    starts = [c for c in cmds if c[0] == "ssh"]
    assert len(starts) == 2
    assert starts[0][1] == "ops@10.0.0.1"
    cmd = starts[0][-1]
    assert "--mode worker" in cmd
    assert "--address 0.0.0.0:10128" in cmd
    assert "/opt/cake-data/alpha-node/model" in cmd
    assert "/opt/cake-data/alpha-node/topology.yml" in cmd
    assert "--name alpha" in cmd
    assert "0.0.0.0:9000" in starts[1][-1]


def test_code_only_sync_without_bundles():
    cmds = plan_commands(TOPO, "/repo", None, "/opt/cake-tpu",
                         "/opt/cake-data")
    assert len(cmds) == 2
    assert all(c[0] == "rsync" for c in cmds)


def test_cli_dry_run_prints_but_does_not_execute(tmp_path):
    topo_path = tmp_path / "t.yml"
    TOPO.save(topo_path)
    r = subprocess.run(
        [sys.executable, "-m", "cake_tpu.tools.deploy",
         "--topology", str(topo_path), "--bundles", "/nonexistent",
         "--start"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 6  # (code + bundle + start) x 2 hosts
    assert "dry run" in r.stderr
    assert all(ln.startswith(("rsync", "ssh")) for ln in lines)


def test_no_host_workers_fails(tmp_path):
    topo_path = tmp_path / "t.yml"
    Topology.from_dict(
        {"m": {"device": 0, "layers": ["model.layers.0-31"]}}
    ).save(topo_path)
    r = subprocess.run(
        [sys.executable, "-m", "cake_tpu.tools.deploy",
         "--topology", str(topo_path)],
        capture_output=True, text=True,
    )
    assert r.returncode == 1
    assert "no host-addressed" in r.stderr
