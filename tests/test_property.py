"""Seeded property tests: core primitives vs brute-force oracles.

Randomized (fixed-seed, deterministic) sweeps over the primitives whose
edge cases the example-based tests cannot enumerate: n-gram proposal,
KV-cache writes (bf16 and int8, scalar and per-row positions), and the
quantization round-trip bound."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.ops.kvcache import dequant_kv, init_cache, update_layer
from cake_tpu.models.config import tiny
from cake_tpu.runtime.speculative import ngram_propose


def _brute_ngram(ctx, n_max, k):
    """Oracle: literally scan for the most recent match, longest n first."""
    L = len(ctx)
    for n in range(min(n_max, L - 1), 0, -1):
        pat = ctx[L - n:]
        for j in range(L - 1 - n, -1, -1):
            if ctx[j: j + n] == pat:
                return ctx[j + n: j + n + k]
    return []


def test_ngram_propose_matches_brute_force_oracle():
    rng = np.random.default_rng(7)
    for trial in range(200):
        L = int(rng.integers(0, 40))
        vocab = int(rng.integers(2, 6))  # small vocab -> many matches
        ctx = rng.integers(0, vocab, L).tolist()
        n_max = int(rng.integers(1, 5))
        k = int(rng.integers(1, 6))
        got = ngram_propose(ctx, n_max, k)
        want = _brute_ngram(ctx, n_max, k)
        assert got == want, (trial, ctx, n_max, k, got, want)


@pytest.mark.parametrize("quant", [None, "int8"])
def test_update_layer_random_positions_match_numpy_oracle(quant):
    """Random write positions (scalar and per-row): exactly the written
    slots change, everything else is untouched, and written values
    round-trip within the int8 bound."""
    cfg = tiny(max_seq_len=16)
    kh, d, s = cfg.num_key_value_heads, cfg.head_dim, 16
    rng = np.random.default_rng(3)
    key = jax.random.PRNGKey(0)
    for trial in range(20):
        b = int(rng.integers(1, 4))
        t = int(rng.integers(1, 4))
        per_row = bool(rng.integers(0, 2))
        cache = init_cache(cfg, batch=b, max_seq=s, quant=quant)
        kc = jax.tree.map(lambda x: x[0], cache.k)
        vc = jax.tree.map(lambda x: x[0], cache.v)
        # pre-populate with a first write so untouched-slot checks are
        # non-trivial
        base_k = jax.random.normal(key, (b, kh, s, d), jnp.bfloat16)
        base_v = jax.random.normal(jax.random.fold_in(key, 1),
                                   (b, kh, s, d), jnp.bfloat16)
        kc, vc = update_layer(kc, vc, base_k, base_v, jnp.int32(0))
        before = np.asarray(dequant_kv(kc, jnp.float32))

        k_new = jax.random.normal(jax.random.fold_in(key, trial + 2),
                                  (b, kh, t, d), jnp.bfloat16)
        v_new = jnp.zeros((b, kh, t, d), jnp.bfloat16)
        if per_row:
            pos = rng.integers(0, s - t + 1, b)
            kc2, _ = update_layer(kc, vc, k_new, v_new,
                                  jnp.asarray(pos, jnp.int32))
        else:
            p = int(rng.integers(0, s - t + 1))
            pos = np.full((b,), p)
            kc2, _ = update_layer(kc, vc, k_new, v_new, jnp.int32(p))
        after = np.asarray(dequant_kv(kc2, jnp.float32))
        tol = 0.05 if quant else 0.02  # int8 quant error vs bf16 rounding
        for bi in range(b):
            lo = int(pos[bi])
            np.testing.assert_allclose(
                after[bi, :, lo: lo + t],
                np.asarray(k_new[bi], np.float32), atol=tol,
            )
            mask = np.ones(s, bool)
            mask[lo: lo + t] = False
            np.testing.assert_array_equal(after[bi, :, mask],
                                          before[bi, :, mask])


def test_quant_kv_bound_random():
    """|dequant(quant(x)) - x| <= per-(token,head) absmax/127 for random
    magnitudes across orders of magnitude."""
    from cake_tpu.ops.kvcache import quant_kv

    rng = np.random.default_rng(11)
    for trial in range(20):
        scale = 10.0 ** rng.integers(-3, 3)
        x = jnp.asarray(
            rng.normal(0, scale, (2, 3, 5, 8)), jnp.float32
        )
        deq = dequant_kv(quant_kv(x), jnp.float32)
        bound = np.abs(np.asarray(x)).max(axis=-1, keepdims=True) / 127
        assert (np.abs(np.asarray(deq - x)) <= bound + 1e-7).all()


def test_bucket_properties():
    """_bucket: >= n, power-of-two growth from the floor, capped at max."""
    from cake_tpu.runtime.generator import _bucket

    for max_seq in (32, 64, 100, 4096):
        for n in range(1, max_seq + 1):
            b = _bucket(n, max_seq)
            assert n <= b <= max_seq or b == max_seq
            if b < max_seq:
                assert b & (b - 1) == 0  # power of two
                if b > 16:  # 16 is the floor; minimality holds above it
                    assert b // 2 < n
