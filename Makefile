# Equivalent of the reference Makefile (build/test/lint/build_release targets,
# Makefile:1-12) for the Python/C++ tree. The reference's ios_bindings/ios
# targets map to `embed` (C-callable worker library, native/cake_embed.cc);
# its rsync deploy targets (Makefile:29-39) map to `deploy` below
# (tools/deploy.py: every topology host, not two hard-coded ones).

PY ?= python

test:
	$(PY) -m pytest tests/ -x -q

# lint = syntax + (optional) pyflakes + cakelint, the project-invariant
# AST checker suite (cake_tpu/analysis): metric-series catalog, engine
# ownership, _GUARDED_BY lock discipline, jit trace purity, wire
# safety, claim lifecycles (acquire/release pairing), and thread
# domains. Fails on any finding not grandfathered (with a justification)
# in analysis-baseline.json. See README "Static analysis".
lint:
	$(PY) -m compileall -q cake_tpu tests bench.py __graft_entry__.py
	@if $(PY) -c 'import pyflakes' 2>/dev/null; then \
	  $(PY) -m pyflakes cake_tpu tests bench.py __graft_entry__.py; fi
	$(PY) -m cake_tpu.analysis --baseline analysis-baseline.json

native: native/libcakewire.so native/libcakeembed.so native/cake_host_demo

native/libcakewire.so: native/cake_wire.cc
	g++ -O2 -fPIC -shared -o $@ $<

# python-config fallback: venv bins often lack python-config; try the
# interpreter-suffixed one first, then python3-config on PATH.
PYCFG := $(shell command -v $(PY)-config || command -v python3-config)

native/libcakeembed.so: native/cake_embed.cc
	@test -n "$(PYCFG)" || { echo "no python-config found"; exit 1; }
	g++ -O2 -fPIC -shared -o $@ $< \
	  $$($(PYCFG) --includes) $$($(PYCFG) --ldflags --embed)

# Runnable C host (the reference's worker-app equivalent): links the embed
# library and serves topology-assigned layers via cake_start_worker.
native/cake_host_demo: native/cake_host_demo.c native/libcakeembed.so
	gcc -O2 -o $@ $< -Lnative -lcakeembed -Wl,-rpath,'$$ORIGIN'

bench:
	CAKE_BENCH_PRESET=tiny JAX_PLATFORMS=cpu $(PY) bench.py

kernel-check:
	$(PY) -m cake_tpu.tools.kernel_check --json-out KERNELS_TPU.json

flash-sweep:
	$(PY) -m cake_tpu.tools.flash_sweep --json-out flash_sweep.json

# int4 decode-gemv diagnosis: block/unpack variants + XLA-s4 vs baselines
int4-sweep:
	$(PY) -m cake_tpu.tools.int4_sweep --json-out int4_sweep.json

# per-hop inter-stage (ppermute) latency/bandwidth — run on a pod slice
ici-probe:
	$(PY) -m cake_tpu.tools.ici_probe --json-out ici_probe.json

# 70B per-stage pricing on one chip (BASELINE configs 4/5): measured
# stage step + prefill, projected v5e-16 tok/s (r5)
stage-slice:
	$(PY) -m cake_tpu.tools.stage_slice --json-out stage_slice.json

# speculation on REAL text: teacher-forced corpus replay (r5) —
# acceptance + tokens/round from actual prose/code n-gram statistics
spec-corpus:
	CAKE_BENCH_SPEC=8 CAKE_BENCH_SPEC_CORPUS=1 CAKE_BENCH_SEQ=2048 \
	  $(PY) bench.py

# live cluster table over every worker's --status-port page (r5)
watch:
	$(PY) -m cake_tpu.tools.watch --topology $(TOPOLOGY) --port 8090

ttft:
	CAKE_BENCH_TTFT=1 $(PY) bench.py

# observability smoke: tiny CPU-only decode with --trace/--metrics-out/
# --flight-log into /tmp, validating every artifact parses. The same case
# runs in the default `make test` path (tests/test_obs.py, non-slow).
trace-smoke:
	$(PY) -m pytest tests/test_obs.py -q -k smoke

# cluster observability smoke: 2-worker CPU loopback asserting the merged
# trace stitches spans from >= 3 pids (master + both workers, clock-
# rebased), and the cluster report names every worker with forward
# p50/p99, RTT, clock offset, and the straggler flag on the slowed one.
cluster-trace-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_zcluster_obs.py -q \
	  -k smoke

# chaos smoke: seeded 2-worker loopback generation that survives one
# injected worker-process kill (+restart inside --recover-deadline) and
# one injected mid-frame stall longer than --op-timeout, with the token
# stream bit-identical to the fault-free run and recovery counters /
# flight flags reflecting each fault; plus the full fault matrix
# (kill/stall/corrupt/truncate/blackhole/refuse at handshake, ping
# plane, prefill, and decode) and the replica-failover loopback.
# (the slow-marked CLI subprocess e2e stays out of the smoke chain —
# `make test` runs it)
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -q -m 'not slow'

# serving smoke: the HTTP request-serving plane (cake_tpu/serve) on a
# tiny random-weight model — >= 4 concurrent SSE clients with per-stream
# output identical to their solo runs, a mid-run arrival admitted without
# stalling running streams, a disconnected client's slot reused, 429 +
# Retry-After under saturation, drain finishing in-flight work, serve.*
# series in /metrics, the tokenizer-less prompt_ids path, and the loadgen
# driver — then the CAKE_BENCH_SERVE end-to-end HTTP tok/s + TTFT row.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serve.py -q -m 'not slow'
	CAKE_BENCH_SERVE=1 CAKE_BENCH_PRESET=tiny CAKE_BENCH_STEPS=16 \
	  JAX_PLATFORMS=cpu $(PY) bench.py

# structured-output smoke: the grammar-constrained decoding plane
# (cake_tpu/constrain) — regex/JSON-schema -> token-DFA round trips,
# disk-cache hits, the no-retrace masked decode path (compile-count
# pinned), schema-constrained serve requests returning valid JSON,
# stop-string SSE holdback, logprobs vs a numpy softmax reference, and
# the bit-identical-unconstrained determinism guard — then the
# CAKE_BENCH_CONSTRAIN constrained-vs-unconstrained HTTP tok/s row
# (loadgen --workload json; every response must json.loads-parse).
constrain-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_constrain.py -q \
	  -m 'not slow'
	CAKE_BENCH_CONSTRAIN=1 CAKE_BENCH_PRESET=tiny CAKE_BENCH_STEPS=16 \
	  JAX_PLATFORMS=cpu $(PY) bench.py

# gateway smoke: the multi-replica routing plane (cake_tpu/gateway) —
# 3-backend loopback fleet with SSE pass-through bit-identical to a
# direct connection, transparent retry + circuit breaker around a killed
# backend, prefix-affinity routing concentrating same-prefix requests on
# one replica (its engine prefix-store hits move, round_robin's do not),
# draining backends routed around with zero 5xx, loadgen --retry-429 and
# --spawn-backends — then the CAKE_BENCH_GATEWAY gateway-vs-direct HTTP
# tok/s + TTFT overhead row (design target: within 10%).
gateway-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_gateway.py -q -m 'not slow'
	CAKE_BENCH_GATEWAY=1 CAKE_BENCH_PRESET=tiny CAKE_BENCH_STEPS=16 \
	  JAX_PLATFORMS=cpu $(PY) bench.py

# paged-KV smoke: the page-pool layout (cake_tpu/kvpool) — paged-vs-slot
# bit-identical streams across steady batch, mid-run admission,
# retire-and-reuse, shared-prefix fan-out (n streams sharing physical
# prefill pages, prefix_hits >= n-1) and constrained streams; pool/
# prefix-tree/LRU units incl. eviction under pressure and admission
# deferral; the no-retrace compile pin — then the CAKE_BENCH_KVPOOL
# churn row (paged vs slot vs steady, legs interleaved; design target:
# churn within 25% of steady on the same config).
kv-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_kvpool.py -q -m 'not slow'
	CAKE_BENCH_KVPOOL=1 CAKE_BENCH_PRESET=tiny CAKE_BENCH_STEPS=16 \
	  JAX_PLATFORMS=cpu $(PY) bench.py

# disagg smoke: the disaggregated prefill/decode tiers (cake_tpu/disagg)
# — KV-page snapshot round trips bit-identical to an uninterrupted
# stream (greedy + sampled, none/bf16/int8 codecs, constrained streams
# resuming mid-grammar, mid-window multi-page), import-into-full-pool
# deferring FIFO-fair, pinned transfer pages surviving eviction storms,
# transfer-channel chaos (kill/truncate/corrupt/stall) recovered by
# retry, and the gateway two-stage route (prefill tier -> transfer ->
# decode resume) bit-identical end to end with transparent re-prefill
# on a dead channel — then the CAKE_BENCH_DISAGG tiered-vs-mixed
# decode-tier TPOT p95 row under the mixed-prefill workload.
disagg-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_disagg.py -q -m 'not slow'
	CAKE_BENCH_DISAGG=1 CAKE_BENCH_PRESET=tiny CAKE_BENCH_STEPS=16 \
	  JAX_PLATFORMS=cpu $(PY) bench.py

# request-tracing smoke: request-scoped fleet tracing + SLO accounting
# (cake_tpu/obs/reqtrace) — traceparent honored/minted, spans connected
# across gateway -> prefill -> transfer -> decode, /v1/requests/<id>
# timelines, burn-rate gauges moving under tight targets, loadgen
# goodput gating.
reqtrace-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_reqtrace.py -q -m 'not slow'

# profiling smoke: the engine profiling plane (cake_tpu/obs/prof) —
# prof-on vs prof-off bit-identical streams, the retrace sentinel
# flagging a steady-state shape change (warn + CAKE_PROF_STRICT raise),
# /debug/prof live on a serve replica, prof.* spans nested under
# request spans in one trace file, and the benchdiff gate semantics.
prof-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_prof.py -q -m 'not slow'

# fleet-elasticity smoke (ISSUE 19): dynamic membership + rolling
# restarts — self-registration leases (idempotent under a 100-thread
# registration storm), explicit deregister-before-503 (zero 503s reach
# a client during a SIGTERM drain), admission shedding/queueing by
# request class under fleet saturation, rolling-restart drains whose
# in-flight streams migrate to a sibling bit-identically, gateway
# restart with empty --backends re-forming the fleet from heartbeats,
# and the control-plane chaos matrix (storm / flap / stale deregister /
# restart) green under a fixed seed — then the live-resize demo:
# loadgen --spawn-backends 2 --resize-to 4 and back under Poisson load
# with zero failed requests.
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py -q -m 'not slow'

# SLO-scheduling smoke (ISSUE 20): priority classes, preemption with
# host-RAM KV spill, per-tenant fairness — interactive jumping a batch
# flood, preempted streams (greedy/sampled/mid-grammar) resuming
# bit-identically from the spill store, the spill chaos matrix
# (resume-storm / spill-store-full / victim-finishes-during-spill),
# admission deferral counted exactly once under spill pressure, the
# /v1/batch bulk endpoint, gateway-vs-direct classed-request parity —
# then the CAKE_BENCH_SLO interactive-TTFT-p95 row: class-aware
# scheduling must beat the FIFO baseline under the mixed-class flood
# or the row fails.
slo-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_slo.py -q -m 'not slow'
	CAKE_BENCH_SLO=1 CAKE_BENCH_PRESET=tiny CAKE_BENCH_STEPS=16 \
	  CAKE_BENCH_BATCH=2 JAX_PLATFORMS=cpu $(PY) bench.py

# bench regression gate: newest bench_results.jsonl row per metric vs
# the best prior run (tools/benchdiff) — nonzero exit past the
# thresholds, so a perf regression fails CI the way a lint finding does.
bench-diff:
	$(PY) -m cake_tpu.tools.benchdiff

# perf smoke (CPU, tier-1 `not slow` cases): the obs disabled-path
# micro-bench and the wire-codec loopback — incl. the bf16 >=1.9x
# bytes-per-decode-token acceptance — plus the obs on/off overhead row
# from the bench ledger path. Chains the cluster smoke: the trailer and
# ping planes ride the same hot path the codec numbers come from — the
# chaos smoke: recovery machinery must keep surviving what the perf
# work keeps touching — and the serve smoke: the network plane sits on
# the same engine hot path. Lint runs first: an invariant violation
# fails faster than any smoke, and the smokes exercise exactly the
# invariants cakelint pins (ownership, deadlines, lock discipline).
perf-smoke: lint cluster-trace-smoke chaos-smoke serve-smoke constrain-smoke gateway-smoke kv-smoke disagg-smoke reqtrace-smoke prof-smoke fleet-smoke slo-smoke
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_perf_smoke.py \
	  tests/test_wire_codec.py -q -m 'not slow'
	CAKE_BENCH_OBS=1 CAKE_BENCH_PRESET=tiny CAKE_BENCH_STEPS=32 \
	  JAX_PLATFORMS=cpu $(PY) bench.py
	$(PY) -m cake_tpu.tools.benchdiff

# Deploy plane (reference Makefile:29-39 sync targets): push code +
# per-worker bundles to every host in TOPOLOGY and optionally start
# workers. Dry-run by default; DEPLOY_FLAGS="--run --start" executes.
TOPOLOGY ?= examples/topology.yaml
BUNDLES ?= ./bundles
deploy:
	$(PY) -m cake_tpu.tools.deploy --topology $(TOPOLOGY) \
	  --bundles $(BUNDLES) $(DEPLOY_FLAGS)

clean:
	rm -f native/*.so native/cake_host_demo
	find . -name __pycache__ -type d -exec rm -rf {} +

.PHONY: test lint native bench kernel-check flash-sweep int4-sweep ici-probe stage-slice spec-corpus watch ttft trace-smoke cluster-trace-smoke chaos-smoke serve-smoke constrain-smoke gateway-smoke kv-smoke disagg-smoke reqtrace-smoke prof-smoke fleet-smoke slo-smoke bench-diff perf-smoke deploy clean
