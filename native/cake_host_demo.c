/* Minimal C host application for the cake-tpu embeddable worker.
 *
 * The runnable-host equivalent of the reference's SwiftUI worker app
 * (cake-ios-worker-app/Cake Worker/ContentView.swift:28-56: pick a folder,
 * call startWorker(name:modelPath:topologyPath:)). A real embedding target
 * (iOS app, daemon, game engine) links libcakeembed.so and makes this one
 * call; this demo is that host reduced to argv.
 *
 * Build:
 *   gcc -O2 -o cake_host_demo cake_host_demo.c -L. -lcakeembed
 * Run:
 *   ./cake_host_demo <name> <model_dir> <topology.yml> [bind_address]
 *
 * Blocks serving ops (like the reference's block_on(Worker::run)) until
 * killed; exits nonzero if the worker fails to start.
 */

#include <stdio.h>

extern int cake_worker_api_version(void);
extern int cake_start_worker(const char *name, const char *model_path,
                             const char *topology_path, const char *address);

int main(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr,
            "usage: %s <name> <model_dir> <topology.yml> [bind_address]\n",
            argv[0]);
    return 2;
  }
  if (cake_worker_api_version() != 1) {
    fprintf(stderr, "unsupported cake embed ABI\n");
    return 3;
  }
  const char *address = argc > 4 ? argv[4] : "";
  fprintf(stderr, "cake_host_demo: starting worker '%s' on %s\n", argv[1],
          address[0] ? address : "0.0.0.0:10128");
  int rc = cake_start_worker(argv[1], argv[2], argv[3], address);
  fprintf(stderr, "cake_host_demo: worker exited rc=%d\n", rc);
  return rc;
}
