// cake-tpu native wire transport.
//
// C++ equivalent of the reference's native Rust communication plane
// (cake-core/src/cake/proto/{mod,message}.rs + the tokio socket handling in
// client.rs/worker.rs): length-prefixed framed messages over TCP with a
// magic word and a hard size cap (proto/mod.rs:4-7, message.rs:118-155).
//
// Differences by design (TPU build):
//  - CRC32 trailer on every frame (the reference has no integrity check;
//    activations crossing DCN between TPU-VM hosts deserve one).
//  - The payload is an opaque byte blob; tensor/header encoding lives one
//    layer up (Python protocol.py or any other binding) so the native lib
//    stays schema-free. On-pod transfers never touch this path at all —
//    they ride ICI inside the compiled program (parallel/pipeline.py).
//
// Exposed as a plain C ABI for ctypes. All functions return >=0 on success,
// negative error codes on failure. Blocking IO with optional timeouts.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x7CA4E701u;  // cake-tpu wire v1
constexpr uint32_t kMaxPayload = 512u * 1024u * 1024u;  // 512 MiB cap

// CRC32 (IEEE, table-driven), computed over type byte + payload.
uint32_t crc32_table[256];
bool crc32_init_done = false;

void crc32_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  crc32_init_done = true;
}

uint32_t crc32(const uint8_t* data, size_t len, uint32_t seed) {
  if (!crc32_init_done) crc32_init();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc32_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

int read_full(int fd, uint8_t* buf, size_t len) {
  size_t got = 0;
  while (got < len) {
    ssize_t n = ::recv(fd, buf + got, len - got, 0);
    if (n == 0) return -2;  // peer closed
    if (n < 0) {
      if (errno == EINTR) continue;
      // SO_RCVTIMEO armed via cw_set_timeout: a wedged peer surfaces as a
      // distinct timeout code, not a generic io error, so the Python layer
      // can raise WireTimeout into the master's reconnect+replay recovery
      if (errno == EAGAIN || errno == EWOULDBLOCK) return -11;
      return -1;
    }
    got += static_cast<size_t>(n);
  }
  return 0;
}

int write_full(int fd, const uint8_t* buf, size_t len) {
  size_t sent = 0;
  while (sent < len) {
    ssize_t n = ::send(fd, buf + sent, len - sent, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) return -11;
      return -1;
    }
    sent += static_cast<size_t>(n);
  }
  return 0;
}

// Keepalive on every connection (both directions of the failure domain):
// a peer that vanished without a FIN — host power-cut, NAT timeout, cable
// pull — otherwise leaves recv() blocked forever and, on the worker side,
// pins that connection's KV caches. Aggressive-ish probing (60s idle,
// 3x10s probes) because the sockets carry per-token decode traffic, not
// long-idle control channels.
void set_keepalive(int fd) {
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof one);
#ifdef TCP_KEEPIDLE
  int idle = 60, intvl = 10, cnt = 3;
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof idle);
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof intvl);
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof cnt);
#endif
}

}  // namespace

extern "C" {

// ---- connection management ------------------------------------------------

// Connect to host:port. Returns fd >= 0 or negative errno-style code.
int cw_connect(const char* host, uint16_t port, int timeout_ms) {
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%u", port);
  struct addrinfo* res = nullptr;
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) return -3;
  int fd = -1;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (timeout_ms > 0) {
      struct timeval tv = {timeout_ms / 1000, (timeout_ms % 1000) * 1000};
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
      // The timeout passed here only bounds connect(); per-op recv/send
      // deadlines are armed by the caller via cw_set_timeout (the Python
      // Connection applies its default — the connect timeout — lazily on
      // first use), so clear it for a known starting state.
      struct timeval zero = {0, 0};
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &zero, sizeof zero);
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &zero, sizeof zero);
      set_keepalive(fd);
      break;
    }
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd >= 0 ? fd : -4;
}

// Bind+listen on addr:port. Returns listening fd or negative.
int cw_listen(const char* addr, uint16_t port, int backlog) {
  // Resolve with getaddrinfo (symmetric with cw_connect): hostnames work and
  // bogus strings fail with -3 instead of inet_addr() silently yielding the
  // broadcast address.
  struct addrinfo hints = {};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  hints.ai_flags = AI_PASSIVE;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%u", port);
  struct addrinfo* res = nullptr;
  const char* node = (addr && *addr) ? addr : nullptr;
  if (getaddrinfo(node, portstr, &hints, &res) != 0 || !res) return -3;
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) {
    freeaddrinfo(res);
    return -1;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  int rc = ::bind(fd, res->ai_addr, res->ai_addrlen);
  freeaddrinfo(res);
  if (rc < 0) {
    ::close(fd);
    return -5;
  }
  if (::listen(fd, backlog > 0 ? backlog : 16) < 0) {
    ::close(fd);
    return -6;
  }
  return fd;
}

// Accept one connection; returns connected fd or negative.
int cw_accept(int listen_fd) {
  int fd = ::accept(listen_fd, nullptr, nullptr);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  set_keepalive(fd);
  return fd;
}

// Arm (or clear, ms=0) the recv/send deadline on an established
// connection. Reads/writes that block past it fail with -11 instead of
// hanging — the hook behind the Python layer's per-op recv deadlines.
int cw_set_timeout(int fd, int timeout_ms) {
  struct timeval tv = {timeout_ms / 1000, (timeout_ms % 1000) * 1000};
  if (setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv) < 0) return -1;
  if (setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv) < 0) return -1;
  return 0;
}

// Local port of a bound socket (for port-0 auto-assign in tests).
int cw_local_port(int fd) {
  struct sockaddr_in sa = {};
  socklen_t len = sizeof sa;
  if (getsockname(fd, reinterpret_cast<struct sockaddr*>(&sa), &len) < 0)
    return -1;
  return ntohs(sa.sin_port);
}

void cw_close(int fd) { ::close(fd); }

// ---- framing --------------------------------------------------------------
// Frame layout (little-endian):
//   u32 magic | u8 msg_type | u32 payload_len | payload | u32 crc32
// crc32 covers msg_type + payload.

int cw_send_msg(int fd, uint8_t msg_type, const uint8_t* payload,
                uint32_t len) {
  if (len > kMaxPayload) return -7;
  uint8_t header[9];
  memcpy(header, &kMagic, 4);
  header[4] = msg_type;
  memcpy(header + 5, &len, 4);
  uint32_t crc = crc32(&msg_type, 1, 0);
  if (len) crc = crc32(payload, len, crc ^ 0);  // chain: seed with prior crc
  if (write_full(fd, header, sizeof header) < 0) return -1;
  if (len && write_full(fd, payload, len) < 0) return -1;
  uint8_t trailer[4];
  memcpy(trailer, &crc, 4);
  if (write_full(fd, trailer, 4) < 0) return -1;
  return 0;
}

// Receive a frame. On success (*payload) is malloc'd (caller frees with
// cw_free), *len set, returns msg_type (>=0). Negative on error:
//  -1 io, -2 closed, -8 bad magic, -7 oversized, -9 crc mismatch,
//  -11 deadline (cw_set_timeout) expired mid-recv.
int cw_recv_msg(int fd, uint8_t** payload, uint32_t* len) {
  uint8_t header[9];
  int rc = read_full(fd, header, sizeof header);
  if (rc < 0) return rc;
  uint32_t magic;
  memcpy(&magic, header, 4);
  if (magic != kMagic) return -8;
  uint8_t msg_type = header[4];
  uint32_t plen;
  memcpy(&plen, header + 5, 4);
  if (plen > kMaxPayload) return -7;
  uint8_t* buf = nullptr;
  if (plen) {
    buf = static_cast<uint8_t*>(malloc(plen));
    if (!buf) return -10;
    rc = read_full(fd, buf, plen);
    if (rc < 0) {
      free(buf);
      return rc;
    }
  }
  uint8_t trailer[4];
  rc = read_full(fd, trailer, 4);
  if (rc < 0) {
    free(buf);
    return rc;
  }
  uint32_t want_crc;
  memcpy(&want_crc, trailer, 4);
  uint32_t crc = crc32(&msg_type, 1, 0);
  if (plen) crc = crc32(buf, plen, crc ^ 0);
  if (crc != want_crc) {
    free(buf);
    return -9;
  }
  *payload = buf;
  *len = plen;
  return msg_type;
}

void cw_free(uint8_t* buf) { free(buf); }

uint32_t cw_magic() { return kMagic; }
uint32_t cw_max_payload() { return kMaxPayload; }

}  // extern "C"
