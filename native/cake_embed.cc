// C-callable embedding shim for cake-tpu workers.
//
// Equivalent of the reference's UniFFI export surface
// (cake-ios/src/lib.rs:11-57): a host application links this library and
// calls cake_start_worker(name, model_path, topology_path, address) to turn
// the process into a cake worker serving its topology-assigned layers. The
// reference bridges Rust->Swift via UniFFI; here the bridge is C -> embedded
// CPython -> cake_tpu.embed.start_worker (the JAX/TPU runtime must live in
// Python, so the FFI boundary wraps the interpreter rather than the model).
//
// Build:  g++ -O2 -fPIC -shared -o libcakeembed.so cake_embed.cc \
//             $(python3-config --includes) $(python3-config --ldflags --embed)
//
// Contract: blocking (like the reference's block_on(Worker::run)); returns
// 0 on clean shutdown, nonzero on error. cake_worker_api_version() lets
// hosts check ABI compatibility.

#include <Python.h>

extern "C" {

int cake_worker_api_version(void) { return 1; }

// Start a worker and block until it stops. Returns 0 on success.
int cake_start_worker(const char *name, const char *model_path,
                      const char *topology_path, const char *address) {
  if (!name || !model_path || !topology_path) return 2;

  const bool owned = !Py_IsInitialized();
  if (owned) Py_InitializeEx(0);

  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 0;

  PyObject *mod = PyImport_ImportModule("cake_tpu.embed");
  if (!mod) {
    PyErr_Print();
    rc = 1;
  } else {
    PyObject *fn = PyObject_GetAttrString(mod, "start_worker");
    PyObject *res =
        fn ? PyObject_CallFunction(
                 fn, "ssss", name, model_path, topology_path,
                 address && *address ? address : "0.0.0.0:10128")
           : nullptr;
    if (!res) {
      PyErr_Print();
      rc = 1;
    }
    Py_XDECREF(res);
    Py_XDECREF(fn);
    Py_DECREF(mod);
  }

  PyGILState_Release(gil);
  if (owned) Py_FinalizeEx();
  return rc;
}

}  // extern "C"
