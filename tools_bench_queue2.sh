#!/bin/bash
# Second-stage wait-then-measure queue (r4 window 3): the rows the tunnel
# drop at ~07:55Z interrupted — the int8-KV long-window serving rerun,
# kernel_check (fixed grouped-int4 + int8-KV-prefill scale specs), and
# flash_sweep's early-frontier decode rows. Same gentle cadence as
# tools_bench_queue.sh; nothing kills an in-flight compile.
set -u
LOG=${LOG:-/tmp/bench_queue2.log}
cd /root/repo

probe() {
  timeout -k 10 240 python -c \
    "import jax; d = jax.devices()[0]; assert d.platform == 'tpu', d; print('healthy:', d.device_kind)" \
    >>"$LOG" 2>&1
}

run_row() {
  echo "=== $(date -u +%FT%TZ) row: $* ===" >>"$LOG"
  env "$@" CAKE_BENCH_PROBE_BUDGET=120 python -u bench.py >>"$LOG" 2>&1
  echo "--- exit $? $(date -u +%FT%TZ)" >>"$LOG"
}

echo "monitor2 start $(date -u +%FT%TZ)" >>"$LOG"
for i in $(seq 1 40); do
  if probe; then
    echo "grant healthy at probe $i $(date -u +%FT%TZ)" >>"$LOG"
    run_row CAKE_BENCH_BATCH=8 CAKE_BENCH_SEQ=4096 CAKE_BENCH_KV=int8
    echo "=== $(date -u +%FT%TZ) kernel_check ===" >>"$LOG"
    timeout -k 30 2400 python -u -m cake_tpu.tools.kernel_check --json-out KERNELS_TPU_r4.json >>"$LOG" 2>&1
    echo "--- kernel_check exit $? $(date -u +%FT%TZ)" >>"$LOG"
    echo "=== $(date -u +%FT%TZ) flash_sweep ===" >>"$LOG"
    timeout -k 30 2400 python -u -m cake_tpu.tools.flash_sweep --json-out FLASH_SWEEP_r4.json >>"$LOG" 2>&1
    echo "--- flash_sweep exit $? $(date -u +%FT%TZ)" >>"$LOG"
    echo "=== $(date -u +%FT%TZ) int4_sweep ===" >>"$LOG"
    timeout -k 30 2400 python -u -m cake_tpu.tools.int4_sweep --json-out INT4_SWEEP_r4.json >>"$LOG" 2>&1
    echo "--- int4_sweep exit $? $(date -u +%FT%TZ)" >>"$LOG"
    echo "queue2 done $(date -u +%FT%TZ)" >>"$LOG"
    exit 0
  fi
  echo "probe $i wedged $(date -u +%FT%TZ); sleeping 20m" >>"$LOG"
  sleep 1200
done
echo "gave up after 40 probes $(date -u +%FT%TZ)" >>"$LOG"
exit 1
